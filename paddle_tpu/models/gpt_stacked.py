"""Stacked-stage GPT — the pipeline-parallel flagship path.

Reference capability: PipelineLayer + 1F1B/interleave scheduling
(fleet/meta_parallel/pp_layers.py:209, pipeline_parallel.py:117-761) makes
pp a first-class hybrid axis next to dp/mp. The TPU-native equivalent is NOT
a per-microbatch p2p driver: block parameters are STACKED on a leading
layer dim (`qkv_w: [L, H, 3H]` etc.), sharded `P("pp", ...)` so each pp
group owns L/pp contiguous layers, and

  * on meshes without pp: one `lax.scan` over the layer dim runs the whole
    depth ("scan-over-layers" — O(1) compile cost in depth);
  * with pp > 1: `distributed.pipeline.pipeline_spmd` rotates microbatch
    activations through the stage shards with a collective-permute each
    tick — steady-state-1F1B utilization, compiled as ONE XLA program that
    composes with dp/mp/sp sharding constraints.

Weight layout/init matches models/gpt.py (same sharding map in the module
docstring there); `from_layered` converts a `GPTForCausalLM` so the two
paths can be checked for loss parity.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor, apply_op
from ..core import ops
from ..nn.layer import Layer
from ..nn import initializer as I
from ..distributed import mesh as _mesh
from ..incubate.nn.functional import fused_linear_cross_entropy_array
from ..ops.attention import functional_attention
from .gpt import GPTConfig

# (param name, per-layer shape fn, pspec over the stacked [L, ...] tensor,
#  depth-scaled init?) — sharding map mirrors models/gpt.py
_BLOCK_PARAMS = [
    ("ln1_w", lambda c: [c.hidden_size], P("pp", None), "ones"),
    ("ln1_b", lambda c: [c.hidden_size], P("pp", None), "zeros"),
    ("qkv_w", lambda c: [c.hidden_size, 3 * c.hidden_size],
     P("pp", None, "mp"), "normal"),
    ("qkv_b", lambda c: [3 * c.hidden_size], P("pp", "mp"), "zeros"),
    ("out_w", lambda c: [c.hidden_size, c.hidden_size],
     P("pp", "mp", None), "scaled"),
    ("out_b", lambda c: [c.hidden_size], P("pp", None), "zeros"),
    ("ln2_w", lambda c: [c.hidden_size], P("pp", None), "ones"),
    ("ln2_b", lambda c: [c.hidden_size], P("pp", None), "zeros"),
    ("up_w", lambda c: [c.hidden_size, c.intermediate_size],
     P("pp", None, "mp"), "normal"),
    ("up_b", lambda c: [c.intermediate_size], P("pp", "mp"), "zeros"),
    ("down_w", lambda c: [c.intermediate_size, c.hidden_size],
     P("pp", "mp", None), "scaled"),
    ("down_b", lambda c: [c.hidden_size], P("pp", None), "zeros"),
]


def _ln(x, w, b, eps):
    mu = x.mean(-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), -1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * w + b


def _block_batch(p, x, cfg: GPTConfig):
    """One transformer block applied to a stage-batched activation
    [S, mb, s, H] with per-stage params (leaves [S, ...])."""
    nh, hd = cfg.num_heads, cfg.head_dim
    eps = cfg.layer_norm_epsilon
    Sdim, mb, s, H = x.shape

    h = _ln(x, p["ln1_w"][:, None, None], p["ln1_b"][:, None, None], eps)
    qkv = jnp.einsum("smth,shk->smtk", h, p["qkv_w"]) \
        + p["qkv_b"][:, None, None]
    qkv = _mesh.shard_constraint(qkv, "pp", "dp", None, "mp")
    qkv = qkv.reshape(Sdim * mb, s, 3, nh, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = _mesh.shard_constraint(q, ("pp", "dp"), None, "mp", None)
    k = _mesh.shard_constraint(k, ("pp", "dp"), None, "mp", None)
    v = _mesh.shard_constraint(v, ("pp", "dp"), None, "mp", None)
    ctx = functional_attention(q, k, v, is_causal=True)
    ctx = ctx.reshape(Sdim, mb, s, nh * hd)
    a = jnp.einsum("smtk,skh->smth", ctx, p["out_w"]) \
        + p["out_b"][:, None, None]
    a = _mesh.shard_constraint(a, "pp", "dp", None, None)
    x = x + a

    h2 = _ln(x, p["ln2_w"][:, None, None], p["ln2_b"][:, None, None], eps)
    u = jnp.einsum("smth,shk->smtk", h2, p["up_w"]) + p["up_b"][:, None, None]
    u = _mesh.shard_constraint(u, "pp", "dp", None, "mp")
    g = jax.nn.gelu(u, approximate=True)
    d = jnp.einsum("smtk,skh->smth", g, p["down_w"]) \
        + p["down_b"][:, None, None]
    d = _mesh.shard_constraint(d, "pp", "dp", None, None)
    return x + d


def _block_single(p, x, cfg: GPTConfig):
    """One transformer block on a single activation [mb, s, H] with
    per-layer params (no stage dim) — the interleaved-pipeline chunk body.
    Constraints name only auto axes (dp/mp): inside
    `pipeline_scan_interleaved` the pp axis is manual (shard_map
    axis_names={'pp'}) and must not appear in sharding constraints."""
    nh, hd = cfg.num_heads, cfg.head_dim
    eps = cfg.layer_norm_epsilon
    mb, s, H = x.shape

    h = _ln(x, p["ln1_w"], p["ln1_b"], eps)
    qkv = jnp.einsum("mth,hk->mtk", h, p["qkv_w"]) + p["qkv_b"]
    qkv = _mesh.shard_constraint(qkv, "dp", None, "mp")
    qkv = qkv.reshape(mb, s, 3, nh, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = _mesh.shard_constraint(q, "dp", None, "mp", None)
    k = _mesh.shard_constraint(k, "dp", None, "mp", None)
    v = _mesh.shard_constraint(v, "dp", None, "mp", None)
    ctx = functional_attention(q, k, v, is_causal=True)
    a = jnp.einsum("mtk,kh->mth", ctx.reshape(mb, s, nh * hd), p["out_w"]) \
        + p["out_b"]
    a = _mesh.shard_constraint(a, "dp", None, None)
    x = x + a

    h2 = _ln(x, p["ln2_w"], p["ln2_b"], eps)
    u = jnp.einsum("mth,hk->mtk", h2, p["up_w"]) + p["up_b"]
    u = _mesh.shard_constraint(u, "dp", None, "mp")
    g = jax.nn.gelu(u, approximate=True)
    d = jnp.einsum("mtk,kh->mth", g, p["down_w"]) + p["down_b"]
    d = _mesh.shard_constraint(d, "dp", None, None)
    return x + d


def _embed(ids, wte, wpe, cfg):
    x = jnp.take(wte, ids, axis=0) + wpe[None, :ids.shape[1]]
    return _mesh.shard_constraint(x, "dp", None, None)


def _stacked_forward_scan(block_tree, x, cfg):
    """Depth via lax.scan over stacked [L, ...] params (no pp)."""
    def body(a, pl):
        pl1 = jax.tree.map(lambda t: t[None], pl)
        return _block_batch(pl1, a[None], cfg)[0], None

    out, _ = jax.lax.scan(body, x, block_tree)
    return out


def _stacked_loss_array(ids, labels, loss_mask, wte, wpe, lnf_w, lnf_b,
                        *block_leaves, cfg: GPTConfig, num_microbatches=None,
                        chunk_size=128, num_virtual=1):
    """Pure-array stacked-GPT loss; pipelines over pp when the mesh has it.
    num_virtual > 1 routes through the interleaved virtual-stage schedule
    (reference PipelineParallelWithInterleave, pipeline_parallel.py:461)."""
    block_tree = dict(zip([n for n, *_ in _BLOCK_PARAMS], block_leaves))
    x = _embed(ids, wte, wpe, cfg)
    pp = _mesh.mesh_axis_size("pp")
    if pp > 1 and num_virtual > 1:
        from ..distributed.pipeline import pipeline_scan_interleaved
        B, s, H = x.shape
        M = num_microbatches or pp
        V = num_virtual
        Lp = pp * V                       # logical pipeline stages
        L = cfg.num_layers
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
        assert L % Lp == 0, \
            f"layers {L} not divisible by pp*num_virtual {Lp}"
        xs = x.reshape(M, B // M, s, H)

        def chunk_fn(ptree, act):
            # ptree leaves [depth_per_chunk, ...] -> scan this chunk's depth
            def body(a, pslice):
                return _block_single(pslice, a, cfg), None

            act, _ = jax.lax.scan(body, act, ptree)
            return act

        # deal logical stages round-robin: sharded row d*V+v must hold
        # logical stage v*pp+d (see pipeline_scan_interleaved contract)
        order = jnp.asarray([v * pp + d for d in range(pp)
                             for v in range(V)], jnp.int32)
        staged = jax.tree.map(
            lambda t: t.reshape((Lp, L // Lp) + t.shape[1:])[order],
            block_tree)
        out = pipeline_scan_interleaved(chunk_fn, staged, xs, axis="pp",
                                        num_virtual=V)
        x = out.reshape(B, s, H)
    elif pp > 1:
        from ..distributed.pipeline import pipeline_spmd
        B, s, H = x.shape
        M = num_microbatches or pp
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
        L = cfg.num_layers
        assert L % pp == 0, f"layers {L} not divisible by pp {pp}"
        xs = x.reshape(M, B // M, s, H)

        def stage_fn(ptree, acts):
            # ptree leaves [S, depth, ...] -> scan the local depth
            depth_first = jax.tree.map(lambda t: jnp.moveaxis(t, 1, 0), ptree)

            def body(a, pslice):
                return _block_batch(pslice, a, cfg), None

            acts, _ = jax.lax.scan(body, acts, depth_first)
            return acts

        staged = jax.tree.map(
            lambda t: t.reshape((pp, L // pp) + t.shape[1:]), block_tree)
        out = pipeline_spmd(stage_fn, staged, xs, axis="pp")
        x = out.reshape(B, s, H)
    else:
        x = _stacked_forward_scan(block_tree, x, cfg)
    x = _ln(x, lnf_w, lnf_b, cfg.layer_norm_epsilon)
    per_tok = fused_linear_cross_entropy_array(
        x, wte, labels, chunk_size=chunk_size)
    if loss_mask is not None:
        per_tok = per_tok * loss_mask
        return per_tok.sum() / jnp.maximum(loss_mask.sum(), 1e-8)
    return per_tok.mean()


class GPTStackedForCausalLM(Layer):
    """Scan-over-layers GPT with pp-shardable stacked block params.

    Same math as `GPTForCausalLM` for dense configs (loss parity asserted in
    tests/test_distributed.py); the pp path additionally needs
    `num_layers % pp == 0` and `batch % num_microbatches == 0`.
    MoE/recompute/sequence-parallel configs use the layered model.
    """

    supports_compiled_pp = True  # fleet.distributed_model dispatch marker

    def __init__(self, config: GPTConfig):
        super().__init__()
        assert config.moe_num_experts == 0, \
            "stacked pipeline path is dense-only; use GPTForCausalLM for MoE"
        self.config = config
        c = config
        L = c.num_layers
        self.wte = self.create_parameter(
            [c.vocab_size, c.hidden_size],
            default_initializer=I.Normal(std=c.initializer_range))
        self.wte.pspec = P("mp", None)
        self.wpe = self.create_parameter(
            [c.max_position_embeddings, c.hidden_size],
            default_initializer=I.Normal(std=c.initializer_range))
        self.wpe.pspec = P()
        self.ln_f_w = self.create_parameter(
            [c.hidden_size], default_initializer=I.Constant(1.0))
        self.ln_f_b = self.create_parameter(
            [c.hidden_size], default_initializer=I.Constant(0.0), is_bias=True)
        self.ln_f_w.pspec = P()
        self.ln_f_b.pspec = P()

        scale = 1.0 / math.sqrt(2 * L)
        for name, shape_fn, pspec, kind in _BLOCK_PARAMS:
            shape = [L] + shape_fn(c)
            if kind == "ones":
                init = I.Constant(1.0)
            elif kind == "zeros":
                init = I.Constant(0.0)
            else:
                init = I.Normal(std=c.initializer_range)
            p = self.create_parameter(shape, default_initializer=init)
            if kind == "scaled":
                p.set_value(p._data * scale)
            p.pspec = pspec
            setattr(self, name, p)

    # -- helpers ---------------------------------------------------------
    def _block_tensors(self):
        return [getattr(self, n) for n, *_ in _BLOCK_PARAMS]

    @classmethod
    def from_layered(cls, model) -> "GPTStackedForCausalLM":
        """Stack a GPTForCausalLM's per-block weights (for parity tests and
        for migrating checkpoints into the pipeline layout)."""
        cfg = model.config
        assert cfg.tie_word_embeddings, "stacked path ties embeddings"
        self = cls(cfg)
        gpt = model.gpt
        self.wte.set_value(gpt.wte.weight._data)
        self.wpe.set_value(gpt.wpe.weight._data)
        self.ln_f_w.set_value(gpt.ln_f.weight._data)
        self.ln_f_b.set_value(gpt.ln_f.bias._data)
        pick = {
            "ln1_w": lambda b: b.ln_1.weight, "ln1_b": lambda b: b.ln_1.bias,
            "qkv_w": lambda b: b.attn.qkv.weight,
            "qkv_b": lambda b: b.attn.qkv.bias,
            "out_w": lambda b: b.attn.out.weight,
            "out_b": lambda b: b.attn.out.bias,
            "ln2_w": lambda b: b.ln_2.weight, "ln2_b": lambda b: b.ln_2.bias,
            "up_w": lambda b: b.mlp.up.weight, "up_b": lambda b: b.mlp.up.bias,
            "down_w": lambda b: b.mlp.down.weight,
            "down_b": lambda b: b.mlp.down.bias,
        }
        for name, *_ in _BLOCK_PARAMS:
            stacked = jnp.stack([pick[name](b)._data for b in gpt.h])
            getattr(self, name).set_value(stacked)
        return self

    # -- API -------------------------------------------------------------
    def forward(self, input_ids):
        cfg = self.config

        def fn(ids, wte, wpe, lnf_w, lnf_b, *leaves):
            tree = dict(zip([n for n, *_ in _BLOCK_PARAMS], leaves))
            x = _embed(ids, wte, wpe, cfg)
            x = _stacked_forward_scan(tree, x, cfg)
            x = _ln(x, lnf_w, lnf_b, cfg.layer_norm_epsilon)
            logits = jnp.einsum("bsh,vh->bsv", x, wte)
            return _mesh.shard_constraint(logits, "dp", None, "mp")

        return apply_op("gpt_stacked_forward", fn,
                        [input_ids, self.wte, self.wpe, self.ln_f_w,
                         self.ln_f_b] + self._block_tensors())

    def loss(self, input_ids, labels, loss_mask=None,
             num_microbatches: Optional[int] = None, chunk_size: int = 128,
             num_virtual: int = 1):
        cfg = self.config
        fn = partial(_stacked_loss_array, cfg=cfg,
                     num_microbatches=num_microbatches, chunk_size=chunk_size,
                     num_virtual=num_virtual)
        if loss_mask is None:
            def fn2(ids, labels_, *rest):
                return fn(ids, labels_, None, *rest)
            args = [input_ids, labels]
        else:
            fn2 = fn
            args = [input_ids, labels, loss_mask]
        return apply_op("gpt_stacked_loss", fn2,
                        args + [self.wte, self.wpe, self.ln_f_w, self.ln_f_b]
                        + self._block_tensors())
