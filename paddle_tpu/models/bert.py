"""BERT model family (capability: the reference's BERT path — dy2static test
models python/paddle/fluid/tests/unittests/dygraph_to_static/bert_dygraph_model.py
and the fused-transformer encoder incubate/nn/layer/fused_transformer.py:725).

TPU-native: same mpu-sharded projections as GPT (qkv/up column-parallel over
`mp`, out/down row-parallel), bf16-ready. Attention takes the Pallas flash
kernel when unmasked and dropout-free; padding-masked or prob-dropout batches
use the fp32-softmax reference path (masked flash is a later optimisation).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

from ..core.tensor import apply_op
from ..core import ops
from ..nn.layer import Layer, LayerList
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layers.common import Embedding, Dropout, Linear
from ..nn.layers.norm import LayerNorm
from ..distributed.mpu import (ColumnParallelLinear, RowParallelLinear,
                               VocabParallelEmbedding)
from ..distributed import mesh as _mesh
from ..ops.attention import functional_attention

__all__ = ["BertConfig", "BertModel", "BertForMaskedLM",
           "BertForSequenceClassification", "BertForPretraining",
           "bert_config"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02
    pad_token_id: int = 0
    num_labels: int = 2
    param_dtype: str = "float32"

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size
        assert self.hidden_size % self.num_heads == 0

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


PRESETS = {
    "bert-base": dict(hidden_size=768, num_layers=12, num_heads=12),
    "bert-large": dict(hidden_size=1024, num_layers=24, num_heads=16),
}


def bert_config(preset: str, **overrides) -> BertConfig:
    cfg = dict(PRESETS[preset])
    cfg.update(overrides)
    return BertConfig(**cfg)


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.Normal(std=config.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size)
        self.word_embeddings.weight.set_value(init(
            [config.vocab_size, config.hidden_size],
            self.word_embeddings.weight.dtype))
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size)
        self.position_embeddings.weight.set_value(init(
            [config.max_position_embeddings, config.hidden_size],
            self.position_embeddings.weight.dtype))
        self.token_type_embeddings = Embedding(
            config.type_vocab_size, config.hidden_size)
        self.token_type_embeddings.weight.set_value(init(
            [config.type_vocab_size, config.hidden_size],
            self.token_type_embeddings.weight.dtype))
        self.layer_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_epsilon)
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = ops.unsqueeze(ops.arange(0, s, dtype="int64"), 0)
        if token_type_ids is None:
            token_type_ids = ops.zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        x = self.layer_norm(x)
        if self.training and self.dropout.p:
            x = self.dropout(x)
        return apply_op("act_shard", lambda a: _mesh.shard_constraint(
            a, "dp", "sp", None), [x])


class BertAttention(Layer):
    """Bidirectional fused-QKV attention with optional padding mask."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.head_dim
        h = config.hidden_size
        init = I.Normal(std=config.initializer_range)
        self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False)
        self.qkv.weight.set_value(init([h, 3 * h], self.qkv.weight.dtype))
        self.out = RowParallelLinear(h, h, input_is_parallel=True)
        self.out.weight.set_value(
            init([h, h], self.out.weight.dtype)
            / math.sqrt(2 * config.num_layers))
        self.dropout = Dropout(config.hidden_dropout)
        self.attn_dropout_p = config.attention_dropout

    def forward(self, x, attention_mask=None, seq_lens=None):
        import jax
        import jax.numpy as jnp
        from ..core import random as _random
        from ..ops.attention import attention_reference

        from ..ops.pallas.fused_mha import fused_mha, use_fused_mha

        nh, hd = self.num_heads, self.head_dim
        qkv = self.qkv(x)
        b, s = qkv.shape[0], qkv.shape[1]
        attn_p = self.attn_dropout_p if self.training else 0.0
        dk = _random.split_key() if attn_p > 0.0 else None

        if (seq_lens is not None and attention_mask is None
                and use_fused_mha(s, nh, hd)
                and _mesh.mesh_axis_size("mp") == 1
                and _mesh.mesh_axis_size("sp") == 1):
            # RIGHT-PADDED batches via explicit lengths (beyond-reference
            # fast path): the fused kernel masks key columns >= len[b] per
            # batch row from an SMEM table — the padding mask never exists
            # as an S x S tensor, and in-kernel dropout still applies.
            # Padded QUERY rows compute garbage that the loss masks out.
            def attend_lens(a, lens):
                seed = None
                if attn_p > 0.0:
                    seed = jax.random.randint(dk, (), 0, 2 ** 31 - 1)
                return fused_mha(a, nh, kv_len=lens, dropout_p=attn_p,
                                 dropout_seed=seed)

            ctx = apply_op("bert_attention", attend_lens, [qkv, seq_lens])
            y = self.out(ctx)
            if self.training and self.dropout.p:
                y = self.dropout(y)
            return y
        if seq_lens is not None:
            # fallback platforms: lengths become a bool keep-mask
            attention_mask = apply_op(
                "lens_to_mask",
                lambda l: (jnp.arange(s)[None, :]
                           < l.astype(jnp.int32)[:, None]).astype(jnp.int32),
                [seq_lens])

        if (attention_mask is None and use_fused_mha(s, nh, hd)
                and _mesh.mesh_axis_size("mp") == 1
                and _mesh.mesh_axis_size("sp") == 1):
            # Whole-sequence fused MHA on the packed projection output with
            # IN-KERNEL PRNG dropout (ops/pallas/fused_mha.py): the S² of
            # attention-probability dropout bits never exist in HBM — that
            # threefry traffic was the single largest cost of the r3 MLM
            # step (~20% MFU). Mask regeneration in backward is validated
            # bit-identical by tools/validate_fused_mha_tpu.py.
            def attend_packed(a):
                seed = None
                if attn_p > 0.0:
                    seed = jax.random.randint(dk, (), 0, 2 ** 31 - 1)
                return fused_mha(a, nh, dropout_p=attn_p, dropout_seed=seed)

            ctx = apply_op("bert_attention", attend_packed, [qkv])
            y = self.out(ctx)
            if self.training and self.dropout.p:
                y = self.dropout(y)
            return y

        qkv = ops.reshape(qkv, [b, s, 3, nh, hd])
        tensor_args = [qkv] if attention_mask is None else [qkv, attention_mask]

        def attend(a, mask=None):
            q, k, v = a[:, :, 0], a[:, :, 1], a[:, :, 2]
            q = _mesh.shard_constraint(q, "dp", "sp", "mp", None)
            k = _mesh.shard_constraint(k, "dp", "sp", "mp", None)
            v = _mesh.shard_constraint(v, "dp", "sp", "mp", None)
            if mask is not None and mask.ndim == 2:
                if jnp.issubdtype(mask.dtype, jnp.floating):
                    mask = mask[:, None, None, :]          # additive [B,Sk]
                else:
                    mask = (mask > 0)[:, None, None, :]    # 0/1 keep [B,Sk]
            # bf16 models store the S×S scores in bf16 (f32 accumulation
            # in the dots and softmax stats — see attention_reference):
            # at S=512 the f32 score arrays are ~400 MB per materialization
            if attn_p == 0.0:
                o = functional_attention(q, k, v, is_causal=False, mask=mask,
                                         score_dtype=q.dtype)
            else:
                o = attention_reference(q, k, v, mask=mask, dropout_p=attn_p,
                                        dropout_key=dk, score_dtype=q.dtype)
            return _mesh.shard_constraint(o, "dp", "sp", "mp", None)

        ctx = apply_op("bert_attention", attend, tensor_args)
        y = self.out(ops.reshape(ctx, [b, s, nh * hd]))
        if self.training and self.dropout.p:
            y = self.dropout(y)
        return y


class BertLayer(Layer):
    """Post-LN encoder block (original BERT ordering)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        init = I.Normal(std=config.initializer_range)
        self.attention = BertAttention(config)
        self.ln_1 = LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.up = ColumnParallelLinear(h, m, gather_output=False)
        self.up.weight.set_value(init([h, m], self.up.weight.dtype))
        self.down = RowParallelLinear(m, h, input_is_parallel=True)
        self.down.weight.set_value(
            init([m, h], self.down.weight.dtype)
            / math.sqrt(2 * config.num_layers))
        self.ln_2 = LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, x, attention_mask=None, seq_lens=None):
        x = self.ln_1(x + self.attention(x, attention_mask,
                                         seq_lens=seq_lens))
        y = self.down(F.gelu(self.up(x), approximate=True))
        if self.training and self.dropout.p:
            y = self.dropout(y)
        return self.ln_2(x + y)


def _tied_logits(h, wte):
    """Vocab-parallel logits against the (tied) embedding table, like
    GPTForCausalLM's tied head."""
    import jax.numpy as jnp
    return apply_op(
        "tied_mlm_head",
        lambda a, w: _mesh.shard_constraint(
            jnp.einsum("bsh,vh->bsv", a, w), "dp", "sp", "mp"),
        [h, wte.weight])


class BertPooler(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = Linear(config.hidden_size, config.hidden_size)

    def forward(self, x):
        return ops.tanh(self.dense(x[:, 0]))


class BertModel(Layer):
    """Backbone: embeddings + encoder + pooler."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = LayerList([BertLayer(config)
                                  for _ in range(config.num_layers)])
        self.pooler = BertPooler(config)
        if config.param_dtype != "float32":
            self.to(dtype=config.param_dtype)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, seq_lens=None):
        """seq_lens (beyond-reference fast path): per-row valid lengths of
        a RIGHT-padded batch — routes the padding mask into the fused MHA
        kernel's SMEM table instead of an S x S mask tensor."""
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.encoder:
            x = layer(x, attention_mask, seq_lens=seq_lens)
        return x, self.pooler(x)


class BertForMaskedLM(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.transform = Linear(config.hidden_size, config.hidden_size)
        self.ln = LayerNorm(config.hidden_size,
                            epsilon=config.layer_norm_epsilon)
        # decoder tied to word embeddings (vocab-parallel logits)
        self.config = config

    def _mlm_hidden(self, seq):
        """The MLM head pipeline shared by logits and the fused loss."""
        return self.ln(F.gelu(self.transform(seq), approximate=True))

    def mlm_logits(self, seq):
        """Shared MLM head: transform -> gelu -> LN -> tied logits."""
        return _tied_logits(self._mlm_hidden(seq),
                            self.bert.embeddings.word_embeddings)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                seq_lens=None):
        seq, _ = self.bert(input_ids, token_type_ids,
                           attention_mask=attention_mask, seq_lens=seq_lens)
        return self.mlm_logits(seq)

    def loss(self, input_ids, labels, token_type_ids=None,
             attention_mask=None, loss_mask=None, chunk_size: int = 256,
             ignore_index: int = -100, seq_lens=None):
        """Fused MLM loss: the tied decoder matmul runs inside the chunked
        linear+softmax-CE (incubate.nn.functional), so [B, S, vocab] logits
        never materialize — same mechanism as GPTForCausalLM.loss().
        Positions with labels == ignore_index are masked out (the standard
        MLM convention)."""
        from ..incubate.nn.functional import fused_linear_cross_entropy
        from ..core import ops
        from .gpt import _masked_mean
        seq, _ = self.bert(input_ids, token_type_ids,
                           attention_mask=attention_mask, seq_lens=seq_lens)
        h = self._mlm_hidden(seq)
        w = self.bert.embeddings.word_embeddings.weight
        safe_labels = ops.where(labels == ignore_index,
                                ops.zeros_like(labels), labels)
        per_tok = fused_linear_cross_entropy(h, w, safe_labels,
                                             chunk_size=chunk_size)
        ignore = ops.cast(labels != ignore_index, "float32")
        mask = ignore if loss_mask is None else ignore * ops.cast(
            loss_mask, "float32")
        return _masked_mean(per_tok, mask)


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout)
        self.classifier = Linear(config.hidden_size, config.num_labels)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        if self.training and self.dropout.p:
            pooled = self.dropout(pooled)
        return self.classifier(pooled)


class BertForPretraining(Layer):
    """MLM + NSP heads."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.mlm = BertForMaskedLM(config)
        self.nsp = Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.mlm.bert(input_ids, token_type_ids,
                                    attention_mask=attention_mask)
        return self.mlm.mlm_logits(seq), self.nsp(pooled)
