"""GPT model family — the flagship (BASELINE.md: GPT-3 1.3B/6.7B hybrid DP+TP).

Reference capability: PaddleNLP-style GPT built from the reference's
mpu layers (fleet/layers/mpu/mp_layers.py) + incubate fused transformer
(incubate/nn/layer/fused_transformer.py:192 FusedMultiHeadAttention, :1021
FusedMultiTransformer). TPU-native: one implementation serves single-chip and
hybrid-parallel — parallelism comes from the mpu layers' PartitionSpecs
(qkv/up = column-parallel over `mp`, out/down = row-parallel), activations
carry dp/sp constraints, attention routes through the Pallas flash kernel,
and rematerialisation is per-block `jax.checkpoint` (distributed.recompute).

Sharding map (scaling-book recipe):
  wte [V, H]        P('mp', None)      vocab-parallel
  wpe [S, H]        replicated
  qkv W [H, 3H]     P(None, 'mp')      heads sharded
  out W [H, H]      P('mp', None)
  mlp up [H, 4H]    P(None, 'mp')
  mlp down [4H, H]  P('mp', None)
  activations [B,S,H] P('dp', 'sp', None); attention heads dim constrained 'mp'
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor, apply_op
from ..core import ops
from ..nn.layer import Layer, LayerList
from ..nn import functional as F
from ..nn.layers.common import Embedding, Dropout
from ..nn.layers.norm import LayerNorm
from ..nn import initializer as I
from ..distributed.mpu import (ColumnParallelLinear, RowParallelLinear,
                               VocabParallelEmbedding, ParallelCrossEntropy)
from ..distributed import mesh as _mesh
from ..distributed.recompute import recompute
from ..ops.attention import functional_attention


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 1024
    hidden_dropout: float = 0.0
    attention_dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    use_recompute: bool = False
    # None = full-segment remat; "dots" = keep MXU outputs, recompute
    # elementwise only (see distributed/recompute.py)
    recompute_policy: Optional[str] = None
    # Mixture-of-experts (GShard-style): num_experts > 0 replaces the MLP
    # of every `moe_every_n_layers`-th block with a routed expert FFN
    # (incubate MoELayer — all_to_all over the ep mesh axis); the router's
    # load-balance aux loss is added to loss() with weight moe_aux_weight
    moe_num_experts: int = 0
    moe_every_n_layers: int = 2
    moe_gate: str = "gshard"
    moe_top_k: Optional[int] = None
    moe_aux_weight: float = 0.01
    # expert-slot headroom over perfectly-balanced routing. 1.25 is the
    # GShard-paper default; the padding slots COMPUTE but don't count as
    # active FLOPs, so it is the largest routing-overhead term (measured
    # decomposition in README's MoE row). 1.0 = tight capacity (more
    # dropped tokens under imbalance — the aux loss keeps the drop rate
    # low once routing converges).
    moe_capacity_factor: float = 1.25
    tie_word_embeddings: bool = True
    param_dtype: str = "float32"
    # "ring" | "ulysses" | None — schedule used when the mesh has sp > 1
    # (exceeds reference: SURVEY §5.7 — no sequence parallelism in snapshot)
    sequence_parallel: str = "ring"

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size
        assert self.hidden_size % self.num_heads == 0

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


# BASELINE.md configs (sizes follow the GPT-3 paper table the reference's
# PaddleNLP entrypoints use)
PRESETS = {
    "gpt3-125m": dict(hidden_size=768, num_layers=12, num_heads=12),
    "gpt3-350m": dict(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt3-1.3b": dict(hidden_size=2048, num_layers=24, num_heads=16),
    "gpt3-2.7b": dict(hidden_size=2560, num_layers=32, num_heads=32),
    "gpt3-6.7b": dict(hidden_size=4096, num_layers=32, num_heads=32),
    "gpt3-13b": dict(hidden_size=5120, num_layers=40, num_heads=40),
}


def gpt_config(preset: str, **overrides) -> GPTConfig:
    cfg = dict(PRESETS[preset])
    cfg.update(overrides)
    return GPTConfig(**cfg)


from contextlib import contextmanager


def _is_q8_cache(cache):
    """True iff a static-cache tuple is the int8 form (k_codes, k_scale,
    v_codes, v_scale, pos[, ragged]). The length check alone is not a safe
    tag — the codes buffer's dtype is — so both dispatch sites (here and
    GPTModel.forward's position offset) verify int8 explicitly and a
    malformed tuple fails loudly instead of reading a scale buffer as the
    position cursor."""
    first = cache[0]
    dt = first._data.dtype if hasattr(first, "_data") else first.dtype
    if len(cache) >= 5:
        if dt != jnp.int8:
            raise ValueError(
                f"static KV-cache tuple of length {len(cache)} must carry "
                f"int8 codes first (got {dt}); bf16/f32 caches are "
                f"(k, v, pos[, ragged])")
        return True
    return False


@contextmanager
def _q8_bind(params, payloads):
    """Tag param Tensors with their barrier'd int8 (codes, scale) payload
    for the duration of a decode trace: matmul/embedding consumers
    (mpu layers, tied head) check `_q8` and stream int8 bytes through the
    Pallas dequant-in-register kernel instead of reading the full-width
    dequantized copy."""
    tagged = []
    try:
        for p, v in zip(params, payloads):
            if v is not None:
                p._q8 = v
                tagged.append(p)
        yield
    finally:
        for p in tagged:
            del p._q8


def _replicate_tree(pa):
    """Pin every leaf of a serving param payload REPLICATED under the
    active mesh (no-op off-mesh). Multi-chip paged serving (ISSUE 16)
    leaves the weights as uncommitted jit inputs, and XLA's auto-spmd is
    then free to invent shardings for them — on the toy engines it picks
    a vocab-sharded wte, which buys a partial-embedding all-reduce and
    per-shard argmax all-gathers the serving CommPlan forbids. Declaring
    the weights replicated keeps the decode inventory at exactly the mpu
    layers' contribution: one mp all-reduce per row-parallel matmul."""
    import jax as _jax
    from ..distributed.mesh import get_mesh, shard_constraint
    if get_mesh() is None:
        return pa
    return _jax.tree_util.tree_map(shard_constraint, pa)


class GPTSelfAttention(Layer):
    """Fused QKV column-parallel attention block."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.head_dim
        self._sequence_parallel = config.sequence_parallel
        h = config.hidden_size
        w_init = I.Normal(std=config.initializer_range)
        self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False)
        self.qkv.weight.set_value(w_init([h, 3 * h], self.qkv.weight.dtype))
        self.out = RowParallelLinear(h, h, input_is_parallel=True)
        self.out.weight.set_value(
            w_init([h, h], self.out.weight.dtype) /
            math.sqrt(2 * config.num_layers))
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, x, cache=None):
        nh, hd = self.num_heads, self.head_dim
        # Paged serving shards the HEAD axis (ISSUE 16): the fused qkv
        # output [B,S,3H] cannot keep a contiguous mp-tiling of 3H through
        # the [B,S,3,nh,hd] split (mp does not divide the leading factor
        # 3), so constraining it to mp here would force the partitioner to
        # insert a collective before every pool write. Instead the paged
        # branch leaves the matmul output unconstrained and pins the HEAD
        # axis right after the reshape — a free replicated->sharded local
        # slice; the redundant per-shard qkv FLOPs are noise against the
        # KV-bandwidth-bound decode step.
        paged = cache is not None and isinstance(cache[0], str)
        qkv = self.qkv(x, shard_output=not paged)       # [B,S,3H]
        b, s = qkv.shape[0], qkv.shape[1]

        new_cache = None
        if paged:
            # PAGED KV-cache serving (ISSUE 5/10): ("paged", k_pool,
            # v_pool, block_tables, lens[, start]) — or, int8 pools,
            # ("paged8", k_codes, k_scale, v_codes, v_scale, tables,
            # lens[, start]). KV lives in a fixed [NB, bs, nh, hd] block
            # pool shared by every request; each row owns blocks named by
            # its table row. One executable serves ANY mix of request
            # lengths — the table/lens/start vectors are data, never
            # shape. `lens` means: true prompt length during prefill
            # (s > 1), tokens already in the cache during decode (s == 1).
            # A trailing `start` (prefix cache) marks SUFFIX prefill:
            # the s > 1 tokens sit at global positions start[b] + i, and
            # attention runs over the pool (cached prefix + suffix)
            # instead of the prompt alone.
            if cache[0] not in ("paged", "paged8"):
                raise ValueError(f"unknown tagged KV-cache kind "
                                 f"{cache[0]!r} (expected 'paged' or "
                                 f"'paged8')")
            q8c = cache[0] == "paged8"
            qkv = ops.reshape(qkv, [b, s, 3, nh, hd])
            # head-axis pin (see note above): [B, S, 3, nh, hd] with nh
            # over mp — no-op off-mesh; under an mp mesh this is the slice
            # that makes every pool write/attend below shard-local
            qkv = apply_op(
                "qkv_head_shard",
                lambda a: _mesh.shard_constraint(
                    a, "dp", None, None, "mp", None), [qkv])
            q = qkv[:, :, 0]
            from ..ops.attention import (paged_cache_write,
                                         paged_cache_write_q8,
                                         paged_prefill_write,
                                         paged_prefill_write_q8,
                                         paged_prefill_mask,
                                         paged_attention,
                                         paged_attention_q8,
                                         paged_prefix_attention,
                                         paged_prefix_attention_q8,
                                         quantize_kv,
                                         attention_q8_cache,
                                         attention_reference)
            if q8c:
                kc, ks, vc, vs, tables, lens = cache[1:7]
                start = cache[7] if len(cache) > 7 else None
                # dispatch on start-presence BEFORE width: a [B, 1]
                # window WITH a start offset is a 1-token suffix-prefill
                # chunk (write at start[b], attend the pool), not a
                # decode step (write at lens[b]) — prefill_chunk=1
                # would otherwise silently corrupt the pool
                if s == 1 and start is None:
                    # decode: quantize the token's K/V at row position
                    # lens[b]; attend cols <= itself via the factored-
                    # scale int8 math (kernel on TPU, gather reference
                    # elsewhere)
                    kc2, ks2 = apply_op(
                        "paged_cache_k_q8", paged_cache_write_q8,
                        [kc, ks, qkv[:, :, 1], tables, lens])
                    vc2, vs2 = apply_op(
                        "paged_cache_v_q8", paged_cache_write_q8,
                        [vc, vs, qkv[:, :, 2], tables, lens])

                    def _attend_paged_q8(qa, kca, ksa, vca, vsa, t, l):
                        return paged_attention_q8(qa, kca, ksa, vca, vsa,
                                                  t, l + 1)

                    ctx = apply_op("paged_attend_q8", _attend_paged_q8,
                                   [q, kc2, ks2, vc2, vs2, tables, lens])
                elif start is not None:
                    # suffix prefill: quantized writes at start[b] + i,
                    # attention over the pool (cached prefix + suffix)
                    kc2, ks2 = apply_op(
                        "paged_prefix_k_q8", paged_prefill_write_q8,
                        [kc, ks, qkv[:, :, 1], tables, start])
                    vc2, vs2 = apply_op(
                        "paged_prefix_v_q8", paged_prefill_write_q8,
                        [vc, vs, qkv[:, :, 2], tables, start])

                    def _attend_prefix_q8(qa, kca, ksa, vca, vsa, t, st):
                        # multi-token selector (ISSUE 11): Pallas kernel
                        # on TPU, gather reference on CPU/parity path
                        return paged_prefix_attention_q8(
                            qa, kca, ksa, vca, vsa, t, st)

                    ctx = apply_op(
                        "paged_prefix_attend_q8", _attend_prefix_q8,
                        [q, kc2, ks2, vc2, vs2, tables, start])
                else:
                    # prompt prefill: quantize-as-written; attention runs
                    # over the prompt's OWN codes — the static int8
                    # path's numerics class (attention_q8_cache), so
                    # int8-paged chains track the static int8 chains
                    kc2, ks2 = apply_op(
                        "paged_prefill_k_q8", paged_prefill_write_q8,
                        [kc, ks, qkv[:, :, 1], tables])
                    vc2, vs2 = apply_op(
                        "paged_prefill_v_q8", paged_prefill_write_q8,
                        [vc, vs, qkv[:, :, 2], tables])

                    def _attend_prompt_q8(qa, ka, va, l):
                        kcod, kscl = quantize_kv(ka)
                        vcod, vscl = quantize_kv(va)
                        mask = paged_prefill_mask(qa.shape[1], l)
                        return attention_q8_cache(qa, kcod, kscl,
                                                  vcod, vscl, mask)

                    ctx = apply_op(
                        "paged_prefill_attend_q8", _attend_prompt_q8,
                        [q, qkv[:, :, 1], qkv[:, :, 2], lens])
                new_cache = ("paged8", kc2.detach(), ks2.detach(),
                             vc2.detach(), vs2.detach(), tables, lens) + \
                    (() if start is None else (start,))
            else:
                kp, vp, tables, lens = cache[1], cache[2], cache[3], \
                    cache[4]
                start = cache[5] if len(cache) > 5 else None
                # same start-before-width dispatch as the q8 branch
                if s == 1 and start is None:
                    # decode step: the token lands at row position
                    # lens[b] and attends to cols <= itself (lens + 1
                    # attendable rows)
                    kp2 = apply_op("paged_cache_k", paged_cache_write,
                                   [kp, qkv[:, :, 1], tables, lens])
                    vp2 = apply_op("paged_cache_v", paged_cache_write,
                                   [vp, qkv[:, :, 2], tables, lens])

                    def _attend_paged(qa, kpa, vpa, t, l):
                        return paged_attention(qa, kpa, vpa, t, l + 1,
                                               score_dtype=qa.dtype)

                    ctx = apply_op("paged_attend", _attend_paged,
                                   [q, kp2, vp2, tables, lens])
                elif start is not None:
                    # suffix prefill (prefix cache): write at
                    # start[b] + i, attend over the pool — causal across
                    # the cached prefix plus the suffix itself
                    kp2 = apply_op("paged_prefix_k", paged_prefill_write,
                                   [kp, qkv[:, :, 1], tables, start])
                    vp2 = apply_op("paged_prefix_v", paged_prefill_write,
                                   [vp, qkv[:, :, 2], tables, start])

                    def _attend_prefix(qa, kpa, vpa, t, st):
                        # multi-token selector (ISSUE 11): Pallas kernel
                        # on TPU, gather reference on CPU/parity path
                        return paged_prefix_attention(
                            qa, kpa, vpa, t, st, score_dtype=qa.dtype)

                    ctx = apply_op("paged_prefix_attend", _attend_prefix,
                                   [q, kp2, vp2, tables, start])
                else:
                    # prefill: write the padded prompt's K/V into the
                    # row's blocks (padding past a row's reservation
                    # lands in the trash block), attend over the prompt
                    # itself — ragged causal, identical numerics class
                    # to the static prefill
                    kp2 = apply_op("paged_prefill_k", paged_prefill_write,
                                   [kp, qkv[:, :, 1], tables])
                    vp2 = apply_op("paged_prefill_v", paged_prefill_write,
                                   [vp, qkv[:, :, 2], tables])

                    def _attend_prompt(qa, ka, va, l):
                        mask = paged_prefill_mask(qa.shape[1], l)
                        return attention_reference(qa, ka, va, mask=mask,
                                                   score_dtype=qa.dtype)

                    ctx = apply_op("paged_prefill_attend", _attend_prompt,
                                   [q, qkv[:, :, 1], qkv[:, :, 2], lens])
                new_cache = ("paged", kp2.detach(), vp2.detach(), tables,
                             lens) + (() if start is None else (start,))
        elif cache is not None and _is_q8_cache(cache):
            # INT8 static-cache decode (cache_dtype="int8"): the bf16 path
            # below is KV-bandwidth-bound at small batch — storing the
            # cache as int8 codes + per-(pos,head) scales halves the KV
            # bytes each decode step streams from HBM. Dequant is a fused
            # elementwise producer of the attention dots (never a
            # materialized bf16 buffer). Reference analog: CacheKV int8 in
            # operators/fused/fused_multi_transformer_op.cu.
            # Tuple: (k_codes, k_scale, v_codes, v_scale, pos[, ragged]).
            qkv = ops.reshape(qkv, [b, s, 3, nh, hd])
            kc, ks, vc, vs, pos = cache[:5]
            ragged = cache[5] if len(cache) >= 6 else None
            q = qkv[:, :, 0]

            from ..ops.attention import (static_cache_update_q8,
                                         static_cache_mask)
            kc2, ks2 = apply_op("static_cache_k_q8", static_cache_update_q8,
                                [kc, ks, qkv[:, :, 1], pos])
            vc2, vs2 = apply_op("static_cache_v_q8", static_cache_update_q8,
                                [vc, vs, qkv[:, :, 2], pos])
            new_cache = (kc2.detach(), ks2.detach(), vc2.detach(),
                         vs2.detach(), pos + s) + (
                (ragged,) if ragged is not None else ())

            def _attend_static_q8(qa, kca, ksa, vca, vsa, p, lens=None):
                from ..ops.attention import attention_q8_cache
                mask = static_cache_mask(
                    kca.shape[1], qa.shape[1], p,
                    prompt_lens=lens,
                    prefill_cap=None if ragged is None else ragged[1])
                return attention_q8_cache(qa, kca, ksa, vca, vsa, mask)

            args = [q, kc2, ks2, vc2, vs2, pos]
            if ragged is not None:
                args.append(ragged[0])
            ctx = apply_op("static_cache_attend_q8", _attend_static_q8, args)
        elif cache is not None and len(cache) >= 3:
            # STATIC-cache decode (TPU-native serving path): fixed-size
            # [B, L_max, nh, hd] buffers + write position — every step has
            # the same shapes, so the whole generation compiles ONCE
            # (generate_static). An optional 4th element
            # (prompt_lens [B], prefill_cap) activates the RAGGED-prompt
            # mask so one program serves any prompt length (VERDICT r3
            # #7a). The growing-cache branch below recompiles per length,
            # which is fine eagerly but ruinous under jit.
            qkv = ops.reshape(qkv, [b, s, 3, nh, hd])
            k_buf, v_buf, pos = cache[0], cache[1], cache[2]
            ragged = cache[3] if len(cache) >= 4 else None
            q = qkv[:, :, 0]

            from ..ops.attention import (static_cache_update,
                                         static_cache_mask)
            k2 = apply_op("static_cache_k", static_cache_update,
                          [k_buf, qkv[:, :, 1], pos])
            v2 = apply_op("static_cache_v", static_cache_update,
                          [v_buf, qkv[:, :, 2], pos])
            new_cache = (k2.detach(), v2.detach(), pos + s) + (
                (ragged,) if ragged is not None else ())

            def _attend_static(qa, ka, va, p, lens=None):
                from ..ops.attention import attention_reference
                mask = static_cache_mask(
                    ka.shape[1], qa.shape[1], p,
                    prompt_lens=lens,
                    prefill_cap=None if ragged is None else ragged[1])
                return attention_reference(qa, ka, va, mask=mask,
                                           score_dtype=qa.dtype)

            args = [q, k2, v2, pos]
            if ragged is not None:
                args.append(ragged[0])
            ctx = apply_op("static_cache_attend", _attend_static, args)
        elif cache is not None:
            # incremental decode: append K/V (reference MultiHeadAttention
            # Cache semantics, nn/layer/transformer.py)
            qkv = ops.reshape(qkv, [b, s, 3, nh, hd])
            k_old, v_old = cache
            q = qkv[:, :, 0]
            k = ops.concat([k_old, qkv[:, :, 1]], axis=1)
            v = ops.concat([v_old, qkv[:, :, 2]], axis=1)
            new_cache = (k.detach(), v.detach())
            ctx = _attend(q, k, v, causal=False)  # q is the tail; mask below
        else:
            # training path hands _qkv_attention the PACKED [B,S,3H]
            # projection; it reshapes (free) per route
            sp = self._sequence_parallel
            ctx = apply_op(
                "gpt_attention",
                lambda a: _qkv_attention(a, nh, hd, sp), [qkv])
        y = self.out(ops.reshape(ctx, [b, ctx.shape[1], nh * hd]))
        if self.training and self.dropout.p:
            y = self.dropout(y)
        if cache is not None:
            return y, new_cache
        return y


def _qkv_attention(qkv3h, nh, hd, sequence_parallel="ring"):
    """qkv3h: PACKED [B, S, 3·nh·hd] projection output."""
    from jax.ad_checkpoint import checkpoint_name
    import jax.numpy as jnp
    qkv3h = checkpoint_name(qkv3h, "qkv_proj")   # save-list hook (recompute.py)
    b, s = qkv3h.shape[0], qkv3h.shape[1]
    H = nh * hd
    sp_active = sequence_parallel and _mesh.mesh_axis_size("sp") > 1
    if (not sp_active and hd == 128 and s % 128 == 0
            and _use_packed_flash()):
        # packed-layout flash (opt-in): q/k/v stay [B,S,H] lane slices
        # of the projection output; dq/dk/dv return in the same layout for
        # the projection weight grad. Removes ~11 head-major layout passes
        # per layer, but measured BREAK-EVEN on the v5e bench chip — the
        # passes overlap with MXU work (see flash_attention_packed).
        q, k, v = qkv3h[:, :, :H], qkv3h[:, :, H:2 * H], qkv3h[:, :, 2 * H:]
        q = _mesh.shard_constraint(q, "dp", "sp", "mp")
        k = _mesh.shard_constraint(k, "dp", "sp", "mp")
        v = _mesh.shard_constraint(v, "dp", "sp", "mp")
        from ..ops.pallas.flash_attention import flash_attention_packed
        out = flash_attention_packed(q, k, v, nh, causal=True)
        out = _mesh.shard_constraint(out, "dp", "sp", "mp")
        out = jnp.reshape(out, (b, s, nh, hd))
        return checkpoint_name(out, "attn_ctx")
    qkv = jnp.reshape(qkv3h, (b, s, 3, nh, hd))
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = _mesh.shard_constraint(q, "dp", "sp", "mp", None)
    k = _mesh.shard_constraint(k, "dp", "sp", "mp", None)
    v = _mesh.shard_constraint(v, "dp", "sp", "mp", None)
    if sp_active:
        # sp>1: keep S sharded end-to-end — ring/ulysses schedule instead of
        # letting XLA all-gather K/V for the dense product (SURVEY §5.7).
        from ..ops.ring_attention import sequence_parallel_attention
        out = sequence_parallel_attention(q, k, v, is_causal=True,
                                          schedule=sequence_parallel)
    else:
        out = functional_attention(q, k, v, is_causal=True)
    out = _mesh.shard_constraint(out, "dp", "sp", "mp", None)
    return checkpoint_name(out, "attn_ctx")


def _use_packed_flash():
    # opt-in: measured break-even on the v5e bench chip (see
    # flash_attention_packed docstring) — default stays the proven
    # head-major kernel. The platform gate keeps the env opt-in from
    # routing a CPU/compile-incapable host into Mosaic.
    import os
    if os.environ.get("PADDLE_TPU_FLASH_PACKED") != "1":
        return False
    import jax
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except RuntimeError:
        return False


def _attend(q, k, v, causal):
    return apply_op("sdpa_cached",
                    lambda a, b_, c: functional_attention(a, b_, c, is_causal=causal),
                    [q, k, v])


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        w_init = I.Normal(std=config.initializer_range)
        self.up = ColumnParallelLinear(h, m, gather_output=False)
        self.up.weight.set_value(w_init([h, m], self.up.weight.dtype))
        self.down = RowParallelLinear(m, h, input_is_parallel=True)
        self.down.weight.set_value(
            w_init([m, h], self.down.weight.dtype) /
            math.sqrt(2 * config.num_layers))
        self.dropout = Dropout(config.hidden_dropout)

    def forward(self, x):
        from ..distributed.recompute import checkpoint_tag
        u = checkpoint_tag(self.up(x), "mlp_up")
        y = self.down(F.gelu(u, approximate=True))
        if self.training and self.dropout.p:
            y = self.dropout(y)
        return y


class GPTBlock(Layer):
    """Pre-LN transformer block; optionally a routed-expert FFN block
    (GShard pattern: every Nth layer is MoE)."""

    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPTSelfAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.is_moe = (config.moe_num_experts > 0 and
                       layer_idx % max(1, config.moe_every_n_layers) ==
                       max(1, config.moe_every_n_layers) - 1)
        if self.is_moe:
            from ..incubate.distributed.models.moe import MoELayer
            self.mlp = MoELayer(config.hidden_size, config.intermediate_size,
                                config.moe_num_experts, gate=config.moe_gate,
                                top_k=config.moe_top_k,
                                capacity_factor=config.moe_capacity_factor)
            # expert FFNs follow the same init convention as the dense
            # path: Normal(initializer_range) in, depth-scaled residual out
            w_init = I.Normal(std=config.initializer_range)
            e, h, m = (config.moe_num_experts, config.hidden_size,
                       config.intermediate_size)
            self.mlp.w1.set_value(w_init([e, h, m], self.mlp.w1.dtype))
            self.mlp.w2.set_value(
                w_init([e, m, h], self.mlp.w2.dtype) /
                math.sqrt(2 * config.num_layers))
            self.moe_drop = Dropout(config.hidden_dropout)
        else:
            self.mlp = GPTMLP(config)

    def forward(self, x, cache=None):
        if cache is not None:
            a, new_cache = self.attn(self.ln_1(x), cache=cache)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, new_cache
        x = x + self.attn(self.ln_1(x))
        y = self.mlp(self.ln_2(x))
        if self.is_moe and self.training and self.moe_drop.p:
            y = self.moe_drop(y)  # dense GPTMLP applies this internally
        return x + y


class GPTModel(Layer):
    """Backbone: embeddings + N blocks + final LN."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        self.wte.weight.set_value(
            I.Normal(std=config.initializer_range)(
                [config.vocab_size, config.hidden_size], self.wte.weight.dtype))
        self.wpe = Embedding(config.max_position_embeddings, config.hidden_size)
        self.drop = Dropout(config.hidden_dropout)
        self.h = LayerList([GPTBlock(config, layer_idx=i)
                            for i in range(config.num_layers)])
        self.ln_f = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        if config.param_dtype != "float32":
            self.to(dtype=config.param_dtype)

    def forward(self, input_ids, position_ids=None, caches=None):
        s = input_ids.shape[1]
        if position_ids is None:
            # int32: positions fit trivially and i64 gathers are 2x-emulated
            # on TPU (MIGRATION.md "Integer dtypes")
            if caches and isinstance(caches[0][0], str):
                # paged caches (prefill_paged/decode_paged pass positions
                # explicitly; this covers direct forward() callers): in
                # prefill (s > 1) the cache's lens vector holds PROMPT
                # lengths and positions start at 0; in decode (s == 1) a
                # row's next position IS its current length
                if s > 1:
                    position_ids = ops.unsqueeze(
                        ops.arange(0, s, dtype="int32"), 0)
                else:
                    lens = caches[0][4]
                    position_ids = ops.unsqueeze(lens, -1) + \
                        ops.arange(0, s, dtype="int32")
            elif caches and len(caches[0]) >= 3:
                # static-cache decode: the write position IS the offset
                # (int8 tuples carry it at index 4, bf16 at index 2)
                pos0 = (caches[0][4] if _is_q8_cache(caches[0])
                        else caches[0][2])
                position_ids = ops.unsqueeze(
                    pos0 + ops.arange(0, s, dtype="int32"), 0)
            else:
                past = caches[0][0].shape[1] if caches else 0
                position_ids = ops.arange(past, past + s, dtype="int32")
                position_ids = ops.unsqueeze(position_ids, 0)
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = apply_op("act_shard", lambda a: _mesh.shard_constraint(
            a, "dp", "sp", None), [x])
        if self.training and self.config.hidden_dropout:
            x = self.drop(x)

        new_caches = [] if caches is not None else None
        aux_losses = []
        for i, block in enumerate(self.h):
            if caches is not None:
                x, c = block(x, cache=caches[i])
                new_caches.append(c)
            elif self.config.use_recompute and self.training:
                if getattr(block, "is_moe", False):
                    # the router aux loss must be an explicit OUTPUT of the
                    # remat region — reading it off the layer afterwards
                    # would leak a tracer out of jax.checkpoint
                    def call(inp, _b=block):
                        y = _b(inp)
                        return y, _b.mlp.aux_loss
                    x, aux = recompute(
                        call, x, policy=self.config.recompute_policy,
                        params=[p for p in block.parameters()
                                if not p.stop_gradient])
                    aux_losses.append(aux)
                else:
                    x = recompute(block, x,
                                  policy=self.config.recompute_policy)
            else:
                x = block(x)
                if getattr(block, "is_moe", False) and \
                        block.mlp.aux_loss is not None:
                    aux_losses.append(block.mlp.aux_loss)
        # router load-balance total of this forward (MoE blocks only)
        self.last_aux_loss = None
        if aux_losses:
            total = aux_losses[0]
            for a in aux_losses[1:]:
                total = total + a
            self.last_aux_loss = total
        x = self.ln_f(x)
        if caches is not None:
            return x, new_caches
        return x


def _validate_cache_dtype(cache_dtype, cdt):
    """Shared generate_static/_ragged check: None, the model dtype, or
    'int8'. Returns True when the int8 KV-cache path is requested."""
    if cache_dtype == "int8":
        return True
    if cache_dtype is not None and jnp.dtype(cache_dtype) != jnp.dtype(cdt):
        raise ValueError(f"cache_dtype must be None, the model dtype, "
                         f"or 'int8'; got {cache_dtype!r}")
    return False


def _coerce_prompt_lens(prompt_lens, cap, name):
    """Shared ragged-serving lens handling: coerce to an int32 device
    array and validate 1 <= len <= cap on the HOST (lens are concrete at
    call time; len 0 would index the padded tail and mask every real
    column, len > cap would un-mask garbage cache rows)."""
    import numpy as _np
    lens_arr = jnp.asarray(
        prompt_lens._data if isinstance(prompt_lens, Tensor)
        else _np.asarray(prompt_lens), jnp.int32)  # lint: allow(tracer-asarray)
    host = _np.asarray(lens_arr)  # lint: allow(tracer-asarray)
    if host.size and (int(host.min()) < 1 or int(host.max()) > cap):
        raise ValueError(
            f"{name}: prompt_lens must satisfy 1 <= len <= P_cap ({cap}); "
            f"got range [{int(host.min())}, {int(host.max())}]")
    return lens_arr


def _wrap_ragged_caches(caches, cap):
    """Flat carry tuples (ending in the raw lens vector) -> the forward's
    cache format, whose ragged marker is the nested (lens, cap) LAST
    element. The single definition keeps the three serving entry points
    (generate_static_ragged, prefill_static, decode_static) from drifting
    on this pytree convention."""
    return [tuple(Tensor(e) for e in c[:-1]) + ((Tensor(c[-1]), cap),)
            for c in caches]


def _unwrap_ragged_caches(new_caches):
    """Inverse of _wrap_ragged_caches for the updated caches the forward
    returns: flatten the nested (lens, cap) back to a trailing lens."""
    return [tuple(e._data for e in c[:-1]) + (c[-1][0]._data,)
            for c in new_caches]


def _check_pool_dtype(pools, cdt, cache_dtype=None):
    """Paged pools carry the model dtype, or — cache_dtype="int8" — the
    (codes int8, scale f32) 4-tuple form (BlockPool(cache_dtype="int8")).
    Returns True for the int8 form; a pool/request mismatch raises so a
    stale pool can never be silently misread."""
    if cache_dtype not in (None, "int8"):
        raise ValueError(f"paged cache_dtype must be None or 'int8'; "
                         f"got {cache_dtype!r}")
    entry = pools[0]
    q8_pool = len(entry) == 4
    if q8_pool != (cache_dtype == "int8"):
        raise ValueError(
            f"paged pool layout ({'int8 codes+scales' if q8_pool else 'model-dtype'}) "
            f"does not match cache_dtype={cache_dtype!r}; rebuild the pool "
            f"with BlockPool(cache_dtype={cache_dtype!r})")
    if q8_pool:
        if entry[0].dtype != jnp.int8 or entry[1].dtype != jnp.float32:
            raise ValueError(f"int8 paged pools must be (int8 codes, f32 "
                             f"scale) pairs; got ({entry[0].dtype}, "
                             f"{entry[1].dtype})")
        return True
    pdt = entry[0].dtype
    if jnp.dtype(pdt) != jnp.dtype(cdt):
        raise ValueError(f"paged KV pools are {pdt}, model is {cdt}; "
                         f"rebuild the pool after model.to(dtype=...)")
    return False


def _make_static_caches(c8, nl, b, L, nh, hd, cdt, lens=None):
    """Per-layer static KV-cache carries for the compiled decode loop.

    bf16/f32: (k, v, pos[, lens]); int8: (k_codes, k_scale, v_codes,
    v_scale, pos[, lens]) — codes int8, scales f32 per (pos, head). The
    lens vector (ragged serving) always rides LAST so model_step wrappers
    can treat it uniformly."""
    if c8:
        base = (jnp.zeros((b, L, nh, hd), jnp.int8),
                jnp.zeros((b, L, nh), jnp.float32),
                jnp.zeros((b, L, nh, hd), jnp.int8),
                jnp.zeros((b, L, nh), jnp.float32), jnp.int32(0))
    else:
        base = (jnp.zeros((b, L, nh, hd), cdt),
                jnp.zeros((b, L, nh, hd), cdt), jnp.int32(0))
    tail = () if lens is None else (lens,)
    return [base + tail for _ in range(nl)]


class GPTForCausalLM(Layer):
    """LM head (tied to wte by default — vocab-parallel logits)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=False)

    def forward(self, input_ids, position_ids=None, caches=None):
        out = self.gpt(input_ids, position_ids, caches=caches)
        x, new_caches = out if caches is not None else (out, None)
        if self.config.tie_word_embeddings:
            q8 = getattr(self.gpt.wte.weight, "_q8", None)
            # paged serving (ISSUE 16): logits stay REPLICATED. The
            # training-style vocab-over-mp constraint would shard this
            # use of wte, and sharding propagates to the parameter — the
            # embedding gather turns into a partial-gather + all-reduce
            # and greedy argmax into per-shard candidates + all-gathers,
            # all of which the serving CommPlan (all-reduce only, from
            # the row-parallel matmuls) forbids. Vocab=128-class logits
            # at decode width are noise next to the KV stream anyway.
            paged = caches is not None and len(caches) > 0 and \
                isinstance(caches[0][0], str)

            def _head_fn(a, w):
                if q8 is not None:
                    from ..ops.pallas.int8_matmul import int8_linear_nd
                    y = int8_linear_nd(a, q8[0], q8[1].reshape(-1),
                                       w_layout="nk")
                else:
                    y = jnp.einsum("bsh,vh->bsv", a, w)
                if paged:
                    return _mesh.shard_constraint(y)
                return _mesh.shard_constraint(y, "dp", "sp", "mp")

            logits = apply_op("tied_lm_head", _head_fn,
                              [x, self.gpt.wte.weight])
        else:
            logits = self.lm_head(x)
        if caches is not None:
            return logits, new_caches
        return logits

    def loss(self, input_ids, labels, loss_mask=None, position_ids=None,
             chunk_size: int = 128):
        """Fused-LM-head training loss: hidden states go straight into the
        chunked linear+softmax-CE (incubate.nn.functional.
        fused_linear_cross_entropy), so [B,S,vocab] logits never exist in
        HBM. Numerically identical to forward()+GPTPretrainingCriterion for
        dense configs; for MoE configs this ALSO adds
        moe_aux_weight * router aux loss (the criterion path needs it
        passed explicitly: crit(..., aux_loss=model.gpt.last_aux_loss))."""
        from ..incubate.nn.functional import fused_linear_cross_entropy
        x = self.gpt(input_ids, position_ids)
        w = (self.gpt.wte.weight if self.config.tie_word_embeddings
             else self.lm_head.weight)
        per_tok = fused_linear_cross_entropy(
            x, w, labels, chunk_size=chunk_size,
            transpose_weight=not self.config.tie_word_embeddings)
        loss = _masked_mean(per_tok, loss_mask)
        aux = getattr(self.gpt, "last_aux_loss", None)
        if aux is not None:
            loss = loss + self.config.moe_aux_weight * aux
        return loss

    def _decode_quantized_params(self):
        """Weight-only int8 payload for decode (cached on the model):
        every >=1M-element 2D matmul weight becomes (int8 codes,
        per-channel f32 scale). Embedding/tied-LM-head table quantizes
        per ROW (both its uses contract over H); projection weights
        [in, out] per OUTPUT column. Decode is weight-bandwidth-bound
        (~2.6 GB/step bf16 at 1.3B), so halving the bytes the scan reads
        is the whole win. Reference anchor: the weight-only int8 path of
        fused_multi_transformer_op.cu serving."""
        cached = getattr(self, "_q8_decode_cache", None)
        if cached is not None:
            return cached
        import os
        min_size = int(os.environ.get("PADDLE_TPU_Q8_DECODE_MIN",
                                      str(1 << 20)))
        wte_id = id(self.gpt.wte.weight)
        qmap = {}
        for i, p_ in enumerate(self.parameters()):
            a = p_._data
            if a.ndim != 2 or a.size < min_size:
                continue
            axis = 1 if id(p_) == wte_id else 0   # reduce over contraction
            w32 = a.astype(jnp.float32)
            s = jnp.max(jnp.abs(w32), axis=axis, keepdims=True) / 127.0
            s = jnp.maximum(s, 1e-12)
            q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
            qmap[i] = (q, s.astype(jnp.float32))
        self._q8_decode_cache = qmap
        return qmap

    def generate_static(self, input_ids, max_new_tokens: int = 16,
                        temperature: float = 0.0, top_k: int = 0,
                        top_p: float = 1.0, max_len: int = None,
                        seed: int = 0, eos_token_id: int = None,
                        weight_dtype: str = None, cache_dtype: str = None):
        """TPU-native generation: static KV-cache buffers + the WHOLE
        prefill-then-decode loop compiled as ONE XLA program (lax.scan over
        decode steps). Same outputs as generate() for greedy decoding; the
        growing-cache generate() retraces at every new length, which is
        fine eagerly but recompiles per token under jit/serving.

        Capability anchor: the reference serves decode via
        fused_multi_transformer_op with a fixed CacheKV workspace
        (operators/fused/fused_multi_transformer_op.cu) — same design:
        preallocated [B, L_max, nh, hd] caches, write cursor, masked
        attention over the full buffer."""
        import jax
        from jax import lax
        from ..jit.api import _swap_params, _trace_guard
        from ..core import autograd

        cfg = self.config
        ids = input_ids if isinstance(input_ids, Tensor) else Tensor(input_ids)
        if max_new_tokens <= 0:
            return ids                      # generate() contract: prompt as-is
        b, p_len = ids.shape
        L = int(max_len or (p_len + max_new_tokens))
        assert L >= p_len + max_new_tokens, "max_len too small"
        params = list(self.parameters())
        cdt = self.gpt.wte.weight._data.dtype
        nh, hd, nl = cfg.num_heads, cfg.head_dim, cfg.num_layers
        q8 = weight_dtype == "int8"
        c8 = _validate_cache_dtype(cache_dtype, cdt)
        qmap = self._decode_quantized_params() if q8 else {}
        # mixed payload -> (full param list, q8 payload list); int8 entries
        # dequantize AT USE behind an optimization barrier so XLA cannot
        # hoist the bf16 reconstruction out of the decode loop, and the
        # barrier'd (codes, scale) pairs ride along for the int8-GEMM
        # consumer hooks (_q8_bind) — when every consumer streams int8 the
        # dequantized copy is dead code and XLA drops it
        expand = self._make_expand(q8, cdt)

        def model_step(pa, tokens, caches):
            ex, pays = expand(pa)
            with _trace_guard(), _swap_params(params, ex), \
                    _q8_bind(params, pays), autograd.no_grad():
                # tuple-generic wrap: (k, v, pos) bf16 or the int8 5-tuple
                # (k_codes, k_scale, v_codes, v_scale, pos)
                logits, nc = self.forward(
                    Tensor(tokens),
                    caches=[tuple(Tensor(e) for e in c) for c in caches])
            return logits._data, [tuple(e._data for e in c) for c in nc]

        def pick(last, key):
            return sample_logits(last, key, temperature=temperature,
                                 top_k=top_k, top_p=top_p)

        def run(pa, prompt, key0):
            caches = _make_static_caches(c8, nl, b, L, nh, hd, cdt)
            logits, caches = model_step(pa, prompt, caches)     # prefill
            key0, k1 = jax.random.split(key0)
            nxt = pick(logits[:, -1].astype(jnp.float32), k1)
            done = (jnp.zeros((b,), bool) if eos_token_id is None
                    else nxt == eos_token_id)

            def body(carry, _):
                # sequences that emitted EOS keep emitting EOS — the scan
                # has static length, so early stop is a per-row mask (the
                # compiled-serving analog of the eager break)
                caches, cur, key, done = carry
                logits, caches = model_step(pa, cur[:, None], caches)
                key, kk = jax.random.split(key)
                new = pick(logits[:, -1].astype(jnp.float32), kk)
                if eos_token_id is not None:
                    new = jnp.where(done, jnp.asarray(eos_token_id,
                                                      new.dtype), new)
                    done = done | (new == eos_token_id)
                return (caches, new, key, done), new

            (_, _, _, _), toks = lax.scan(body, (caches, nxt, key0, done),
                                          None, length=max_new_tokens - 1)
            gen = jnp.concatenate([nxt[:, None], jnp.moveaxis(toks, 0, 1)],
                                  axis=1)
            return jnp.concatenate([prompt.astype(jnp.int64),
                                    gen.astype(jnp.int64)], axis=1)

        # cache the jitted runner per static signature — a fresh closure
        # every call would retrace AND recompile every generation. The
        # param dtype is part of the key: the cached closure bakes cdt
        # into its KV-buffer allocation, so a model.to(dtype=...) after
        # the first call must miss the cache, not reuse stale buffers.
        # LRU-capped compiled-runner cache: a serving loop over ragged
        # prompt lengths would otherwise accumulate compilations without
        # bound (advisor r3). Callers that want ONE executable for all
        # prompt lengths should pass max_len=L (fixed) — prefill is
        # kv_len-masked to p_len, so any prompt <= L reuses the program.
        sig = (b, p_len, int(max_new_tokens), L, float(temperature),
               int(top_k), float(top_p),
               None if eos_token_id is None else int(eos_token_id), str(cdt),
               "q8" if q8 else "full", "c8" if c8 else "cfull")
        fn = self._gen_cache_get(sig, lambda: jax.jit(run))
        payload = tuple(qmap[i] if i in qmap else p._data
                        for i, p in enumerate(params)) if q8 else \
            tuple(p._data for p in params)
        out = fn(payload, ids._data, jax.random.PRNGKey(seed))
        return Tensor(out)

    # ----------------------------------------------- prefix-reuse serving
    def prefill_static(self, input_ids, max_len: int,
                       weight_dtype: str = None, cache_dtype: str = None,
                       prompt_lens=None):
        """Run the prompt ONCE and return a reusable prefill state.

        Serving loops that share a prompt prefix (a system prompt, a
        few-shot template, best-of-N sampling over one prompt) pay the
        prefill forward a single time; every `decode_static` call then
        continues from the returned state without recomputing it. The
        reference serves the same pattern by retaining the CacheKV
        workspace between fused_multi_transformer launches
        (operators/fused/fused_multi_transformer_op.cu).

        Returns an opaque state dict. The state is immutable — each
        decode_static writes into its own copy of the cache buffers (XLA
        copy-on-write), so one prefill fans out to any number of
        continuations.

        prompt_lens (optional, [B] host ints): RAGGED prompts right-padded
        to input_ids' width — rows in [len, width) hold garbage k/v that
        the per-row cache masks exclude, and each row's continuation
        starts at its TRUE length (same contract as
        generate_static_ragged)."""
        import jax
        from ..jit.api import _swap_params, _trace_guard
        from ..core import autograd

        cfg = self.config
        ids = input_ids if isinstance(input_ids, Tensor) else Tensor(input_ids)
        b, p_len = ids.shape
        if max_len <= p_len:
            raise ValueError(f"max_len ({max_len}) must exceed the prompt "
                             f"length ({p_len}) to leave room for decode")
        params = list(self.parameters())
        cdt = self.gpt.wte.weight._data.dtype
        nh, hd, nl = cfg.num_heads, cfg.head_dim, cfg.num_layers
        q8 = weight_dtype == "int8"
        c8 = _validate_cache_dtype(cache_dtype, cdt)
        qmap = self._decode_quantized_params() if q8 else {}
        expand = self._make_expand(q8, cdt)

        lens_arr = None
        if prompt_lens is not None:
            lens_arr = _coerce_prompt_lens(prompt_lens, p_len,
                                           "prefill_static")

        def run(pa, prompt, lens):
            caches = _make_static_caches(c8, nl, b, max_len, nh, hd, cdt,
                                         lens=lens)
            ex, pays = expand(pa)
            with _trace_guard(), _swap_params(params, ex), \
                    _q8_bind(params, pays), autograd.no_grad():
                if lens is None:
                    logits, nc = self.forward(
                        Tensor(prompt),
                        caches=[tuple(Tensor(e) for e in c)
                                for c in caches])
                    nc_out = [tuple(e._data for e in c) for c in nc]
                    last = logits._data[:, -1].astype(jnp.float32)
                else:
                    pos0 = jnp.broadcast_to(
                        jnp.arange(p_len, dtype=jnp.int32)[None], (b, p_len))
                    logits, nc = self.forward(
                        Tensor(prompt), position_ids=Tensor(pos0),
                        caches=_wrap_ragged_caches(caches, p_len))
                    nc_out = _unwrap_ragged_caches(nc)
                    last = logits._data[jnp.arange(b),
                                        lens - 1].astype(jnp.float32)
            return nc_out, last

        sig = ("prefill", b, p_len, int(max_len), str(cdt),
               "q8" if q8 else "full", "c8" if c8 else "cfull",
               "ragged" if lens_arr is not None else "fixed")
        fn = self._gen_cache_get(sig, lambda: jax.jit(run))
        payload = tuple(qmap[i] if i in qmap else p._data
                        for i, p in enumerate(params)) if q8 else \
            tuple(p._data for p in params)
        caches, last_logits = fn(payload, ids._data, lens_arr)
        # cdt is captured at PREFILL time: a model.to(dtype=...) between
        # prefill and decode must not mix the state's arrays with a new
        # live dtype (decode_static validates against this). param_ids
        # snapshots the identity of the prefill-time parameter arrays so
        # decode_static can reject decode against mutated weights (ADVICE
        # r5): decode replays state["payload"], i.e. the PREFILL-time
        # weights, so silently continuing after an optimizer step would
        # sample from a model the caller no longer holds.
        return {"caches": caches, "last_logits": last_logits,
                "prompt": ids._data, "max_len": int(max_len),
                "q8": q8, "c8": c8, "payload": payload, "cdt": str(cdt),
                "param_ids": tuple(id(p._data) for p in params),
                "lens": lens_arr}

    def decode_static(self, state, max_new_tokens: int,
                      temperature: float = 0.0, top_k: int = 0,
                      top_p: float = 1.0, seed: int = 0,
                      eos_token_id: int = None, return_state: bool = False,
                      donate_cache: bool = False):
        """Continue from a `prefill_static` state: ONE compiled lax.scan of
        fixed-shape decode steps. Repeated calls (different seeds /
        sampling configs) reuse the SAME prefill — greedy output equals
        the tail of `generate_static` on the same prompt.

        return_state=True additionally returns a RESUMABLE state: the next
        decode_static call on it continues exactly where this one stopped
        (the un-written last token rides along as `pending`, the EOS mask
        as `done`, and ragged wpe positions offset by `generated`). Chunked
        greedy decode is bit-identical to one decode of the summed length
        — the serving engine decodes [1, chunk, chunk, ...] to measure
        time-to-first-token truthfully and to stop early once every row
        finished, with each chunk size compiling once. Sampled
        (temperature > 0) chunked output differs from one-shot by design:
        every call seeds its own PRNG stream.

        donate_cache=True (requires return_state) DONATES the state's KV
        buffers to XLA, which then updates them in place instead of
        re-threading the whole cache tuple by value every chunk — the
        serving engine's chunk loop sets it. It CONSUMES the input state:
        the passed-in state's cache arrays are invalid afterwards, so the
        prefill fan-out pattern (one prefill, many continuations) must
        keep the default. Tokens are bit-identical either way (donation is
        an aliasing hint, not a numerics change)."""
        import jax
        from jax import lax
        from ..jit.api import _swap_params, _trace_guard
        from ..core import autograd

        b, p_len = state["prompt"].shape
        L = state["max_len"]
        resume = state.get("pending") is not None
        gen0 = int(state.get("generated", 0))
        if donate_cache and not return_state:
            raise ValueError("donate_cache=True needs return_state=True: "
                             "without the returned state the donated "
                             "buffers would simply be destroyed")
        if max_new_tokens <= 0:
            raise ValueError("decode_static needs max_new_tokens >= 1 "
                             "(the state already holds the prompt)")
        # capacity: the LAST sampled token is returned but never written to
        # the KV cache (scan steps 1..max_new_tokens-1 write positions
        # p_len..p_len+max_new_tokens-2), so a state sized L admits
        # p_len + max_new_tokens - 1 cache rows — not p_len + max_new_tokens
        # (ADVICE r5: the stricter check wasted the buffer's last row).
        # A resumed state's pending token occupies the cursor row first, so
        # its `generated` count joins the prompt on the left side.
        if p_len + gen0 + max_new_tokens - 1 > L:
            raise ValueError(
                f"decode_static: prompt ({p_len}) + generated ({gen0}) + "
                f"max_new_tokens ({max_new_tokens}) needs "
                f"{p_len + gen0 + max_new_tokens - 1} cache rows, "
                f"exceeding the prefill state's max_len ({L})")
        params = list(self.parameters())
        cdt = self.gpt.wte.weight._data.dtype
        if str(cdt) != state["cdt"]:
            raise ValueError(
                f"decode_static: the model's dtype changed since prefill "
                f"({state['cdt']} -> {cdt}); re-run prefill_static")
        # stale-weight guard (ADVICE r5): decode replays the PREFILL-time
        # parameter snapshot carried in the state. If the live parameter
        # arrays are no longer the ones prefill saw (optimizer step,
        # set_value, load_dict), continuing would silently sample from
        # stale weights — reject instead. Identity comparison is exact for
        # the full-precision path (the state's payload pins the prefill
        # arrays alive, so their ids cannot be recycled); under q8 the
        # un-quantized prefill arrays are not pinned, so a freed id could
        # in principle be recycled by a replacement array — a best-effort
        # guard there (every param would have to collide, in order).
        snap = state.get("param_ids")
        if snap is not None and tuple(id(p._data) for p in params) != snap:
            raise ValueError(
                "decode_static: the model's parameters changed since "
                "prefill_static; decode would replay the prefill-time "
                "weight snapshot. Re-run prefill_static after mutating "
                "weights (or decode before updating them).")
        q8 = state["q8"]
        ragged = state.get("lens") is not None
        expand = self._make_expand(q8, cdt)

        def model_step(pa, tokens, caches, pos_ids=None):
            ex, pays = expand(pa)
            with _trace_guard(), _swap_params(params, ex), \
                    _q8_bind(params, pays), autograd.no_grad():
                if ragged:
                    logits, nc = self.forward(
                        Tensor(tokens),
                        position_ids=Tensor(pos_ids),
                        caches=_wrap_ragged_caches(caches, p_len))
                    return logits._data, _unwrap_ragged_caches(nc)
                logits, nc = self.forward(
                    Tensor(tokens),
                    caches=[tuple(Tensor(e) for e in c) for c in caches])
                return logits._data, [tuple(e._data for e in c)
                                      for c in nc]

        def pick(last, key):
            return sample_logits(last, key, temperature=temperature,
                                 top_k=top_k, top_p=top_p)

        def body_fn(pa, lens):
            # shared scan body: `step` counts generated tokens 1-indexed, so
            # the token fed at `step` sits at sequence position
            # lens + step - 1 in its (ragged) row
            def body(carry, step):
                caches, cur, key, done = carry
                pos = None if lens is None else (lens + step - 1)[:, None]
                logits, caches = model_step(pa, cur[:, None], caches, pos)
                key, kk = jax.random.split(key)
                new = pick(logits[:, -1].astype(jnp.float32), kk)
                new = new.astype(jnp.int32)
                if eos_token_id is not None:
                    new = jnp.where(done, jnp.asarray(eos_token_id,
                                                      new.dtype), new)
                    done = done | (new == eos_token_id)
                return (caches, new, key, done), new
            return body

        def run(pa, caches, last_logits, lens, done0, key0):
            key0, k1 = jax.random.split(key0)
            nxt = pick(last_logits, k1).astype(jnp.int32)
            done = done0 if eos_token_id is None else \
                (done0 | (nxt == eos_token_id))
            (caches, _, _, done), toks = lax.scan(
                body_fn(pa, lens), (caches, nxt, key0, done),
                jnp.arange(1, max_new_tokens, dtype=jnp.int32))
            out = jnp.concatenate([nxt[:, None], jnp.moveaxis(toks, 0, 1)],
                                  axis=1).astype(jnp.int64)
            # stateless callers get a tokens-only executable — the cache
            # pytree must not ride out as live output buffers they drop
            return (out, caches, done) if return_state else out

        def run_resume(pa, caches, pending, lens, g0, done0, key0):
            # the resumed chunk has no un-sampled logits to start from: it
            # FEEDS the previous chunk's pending token first. The body's
            # invariant is `step s feeds the s-th generated token` (at row
            # position lens + s - 1); pending is token gen0, so this
            # chunk's steps are gen0 .. gen0+max_new_tokens-1. gen0 rides
            # in as a DATA input (g0), not a trace constant: one resume
            # executable per chunk SIZE serves every resume depth, so a
            # serving loop decoding [1, c, c, ...] compiles two decode
            # programs total however long the schedule is.
            (caches, _, _, done), toks = lax.scan(
                body_fn(pa, lens),
                (caches, pending.astype(jnp.int32), key0, done0),
                g0 + jnp.arange(max_new_tokens, dtype=jnp.int32))
            out = jnp.moveaxis(toks, 0, 1).astype(jnp.int64)
            return (out, caches, done) if return_state else out

        # return_state is part of the signature: the stateless executable
        # returns ONLY the tokens (as before resume existed), the stateful
        # one adds the cache pytree + done mask it hands to the next chunk
        sig = ("decode", b, p_len, L, int(max_new_tokens),
               float(temperature), int(top_k), float(top_p),
               None if eos_token_id is None else int(eos_token_id),
               str(cdt), "q8" if q8 else "full",
               "c8" if state["c8"] else "cfull",
               "ragged" if ragged else "fixed",
               "resume" if resume else "fresh",
               "st" if return_state else "nost",
               "don" if donate_cache else "nodon")
        fn = self._gen_cache_get(
            sig, lambda: jax.jit(
                run_resume if resume else run,
                donate_argnums=(1,) if donate_cache else ()))
        done0 = state.get("done")
        if done0 is None:
            done0 = jnp.zeros((b,), bool)
        args = (state["payload"], state["caches"],
                state["pending"] if resume else state["last_logits"],
                state.get("lens"))
        if resume:
            args += (jnp.int32(gen0),)
        res = fn(*args, done0, jax.random.PRNGKey(seed))
        if not return_state:
            return Tensor(res)
        toks, caches, done = res
        new_state = dict(state)
        new_state.update(caches=caches, pending=toks[:, -1], done=done,
                         generated=gen0 + int(max_new_tokens),
                         last_logits=None)
        return Tensor(toks), new_state

    # ------------------------------------------------ paged-pool serving
    def prefill_paged(self, input_ids, prompt_lens, pools, block_tables,
                      temperature: float = 0.0, top_k: int = 0,
                      top_p: float = 1.0, seed: int = 0,
                      weight_dtype: str = None, cache_dtype: str = None,
                      start=None):
        """Prefill ragged prompts INTO a paged KV block pool (ISSUE 5).

        input_ids [n, P_cap] right-padded prompts; prompt_lens [n] true
        lengths; pools = per-layer (k_pool, v_pool) — or, for
        ``cache_dtype="int8"``, (k_codes, k_scale, v_codes, v_scale) —
        from `inference.kv_cache.BlockPool.make_pools()`; block_tables
        [n, MB] int32 rows naming each prompt's allocated blocks
        (0 = trash).

        Writes every prompt's K/V into its blocks and returns
        ``(pools', first_token [n] int32)`` — the pools are DONATED
        (updated in place by XLA; the passed-in arrays are invalid after
        the call) and first_token is already sampled from each row's
        last-real-position logits, so TTFT is known the moment this call
        syncs. One executable serves any prompt lengths <= P_cap: the
        table/lens vectors are data inputs, and the serving engine uses a
        fixed n (1 per spliced admission) so steady-state traffic adds
        zero compilations.

        `start` [n] int32 (prefix cache, ISSUE 10) switches to SUFFIX
        prefill: input_ids then holds only the yet-uncached suffix
        (right-padded; prompt_lens = suffix lengths), row positions run
        start[b] + i, and attention covers the pool — the cached prefix
        blocks mapped into the row's table plus the suffix itself. Still
        one executable for any (start, suffix) mix: both are data."""
        import jax
        from ..jit.api import _swap_params, _trace_guard
        from ..core import autograd

        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(input_ids)
        b, p_cap = ids.shape
        lens_arr = _coerce_prompt_lens(prompt_lens, p_cap, "prefill_paged")
        tables = jnp.asarray(
            block_tables._data if isinstance(block_tables, Tensor)
            else block_tables, jnp.int32)
        if tables.shape[0] != b:
            raise ValueError(f"prefill_paged: block_tables rows "
                             f"({tables.shape[0]}) != batch ({b})")
        ofs = start is not None
        start_arr = None if not ofs else jnp.asarray(
            start._data if isinstance(start, Tensor) else start, jnp.int32)
        params = list(self.parameters())
        cdt = self.gpt.wte.weight._data.dtype
        c8 = _check_pool_dtype(pools, cdt, cache_dtype)
        tag = "paged8" if c8 else "paged"
        q8 = weight_dtype == "int8"
        qmap = self._decode_quantized_params() if q8 else {}
        expand = self._make_expand(q8, cdt)

        def run(pa, pools, prompt, lens, tbl, key0, st=None):
            pa = _replicate_tree(pa)
            ex, pays = expand(pa)
            with _trace_guard(), _swap_params(params, ex), \
                    _q8_bind(params, pays), autograd.no_grad():
                tail = () if st is None else (Tensor(st),)
                caches = [(tag,) + tuple(Tensor(p) for p in layer) +
                          (Tensor(tbl), Tensor(lens)) + tail
                          for layer in pools]
                pos0 = jnp.broadcast_to(
                    jnp.arange(p_cap, dtype=jnp.int32)[None], (b, p_cap))
                if st is not None:
                    pos0 = pos0 + st.astype(jnp.int32)[:, None]
                logits, nc = self.forward(
                    Tensor(prompt), position_ids=Tensor(pos0),
                    caches=caches)
            n_pool = 4 if c8 else 2
            new_pools = [tuple(e._data for e in c[1:1 + n_pool])
                         for c in nc]
            last = logits._data[jnp.arange(b), lens - 1].astype(jnp.float32)
            key0, k1 = jax.random.split(key0)
            nxt = sample_logits(last, k1, temperature=temperature,
                                top_k=top_k, top_p=top_p).astype(jnp.int32)
            return new_pools, nxt

        nb, bs = pools[0][0].shape[0], pools[0][0].shape[1]
        sig = ("paged_prefill", b, p_cap, nb, bs, int(tables.shape[1]),
               float(temperature), int(top_k), float(top_p), str(cdt),
               "q8" if q8 else "full", "c8" if c8 else "fp",
               "ofs" if ofs else "abs", _mesh.mesh_axis_size("mp"))
        fn = self._gen_cache_get(
            sig, lambda: jax.jit(run, donate_argnums=(1,)))
        payload = tuple(qmap[i] if i in qmap else p._data
                        for i, p in enumerate(params)) if q8 else \
            tuple(p._data for p in params)
        args = (payload, pools, ids._data, lens_arr, tables,
                jax.random.PRNGKey(seed))
        pools2, nxt = fn(*args, start_arr) if ofs else fn(*args)
        return pools2, Tensor(nxt)

    def decode_paged(self, pools, block_tables, lens, pending, done,
                     max_new_tokens: int, temperature: float = 0.0,
                     top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                     eos_token_id: int = None, weight_dtype: str = None,
                     cache_dtype: str = None):
        """One compiled chunk of ragged decode against the paged pool.

        Feeds `pending` (each row's last sampled-but-unwritten token,
        same resume convention as decode_static's return_state), writes
        its K/V at each row's own position `lens[b]`, and scans
        `max_new_tokens` fixed-shape steps. block_tables/lens/pending/done
        are DATA inputs — the serving engine edits them per batch slot
        between chunks (slot-level splicing) without ever changing a
        compiled signature; one executable per chunk SIZE serves every mix
        of request lengths and every resume depth. The pools are DONATED
        (in-place update; the passed-in arrays are invalid afterwards).

        Returns ``(tokens [B, max_new_tokens] int64, pools', lens',
        done')``. Greedy chains are bit-identical per row to
        generate_static_ragged — attention masks make batch company and
        chunking value-invariant, and each row's positions are its own
        true lengths. (Caveat: bf16 models on TPU route through the
        f32-score Pallas kernel while the static path stores bf16 scores,
        so parity there is approximate near argmax ties; exact when both
        sides share a numerics class — f32 models, or the CPU reference
        path.)"""
        import jax
        from jax import lax
        from ..jit.api import _swap_params, _trace_guard
        from ..core import autograd

        if max_new_tokens <= 0:
            raise ValueError("decode_paged needs max_new_tokens >= 1")
        tables = jnp.asarray(
            block_tables._data if isinstance(block_tables, Tensor)
            else block_tables, jnp.int32)
        b = tables.shape[0]
        lens_arr = jnp.asarray(
            lens._data if isinstance(lens, Tensor) else lens, jnp.int32)
        pending_arr = jnp.asarray(
            pending._data if isinstance(pending, Tensor) else pending,
            jnp.int32)
        done_arr = jnp.asarray(
            done._data if isinstance(done, Tensor) else done, bool)
        params = list(self.parameters())
        cdt = self.gpt.wte.weight._data.dtype
        c8 = _check_pool_dtype(pools, cdt, cache_dtype)
        tag = "paged8" if c8 else "paged"
        n_pool = 4 if c8 else 2
        q8 = weight_dtype == "int8"
        qmap = self._decode_quantized_params() if q8 else {}
        expand = self._make_expand(q8, cdt)

        def pick(last, key):
            return sample_logits(last, key, temperature=temperature,
                                 top_k=top_k, top_p=top_p)

        def run(pa, pools, tbl, lens_, pending_, done_, key0):
            pa = _replicate_tree(pa)

            def model_step(tokens, pools, ln):
                ex, pays = expand(pa)
                with _trace_guard(), _swap_params(params, ex), \
                        _q8_bind(params, pays), autograd.no_grad():
                    caches = [(tag,) + tuple(Tensor(p) for p in layer) +
                              (Tensor(tbl), Tensor(ln))
                              for layer in pools]
                    logits, nc = self.forward(
                        Tensor(tokens), position_ids=Tensor(ln[:, None]),
                        caches=caches)
                return (logits._data,
                        [tuple(e._data for e in c[1:1 + n_pool])
                         for c in nc])

            def body(carry, _):
                pools, ln, cur, key, dn = carry
                logits, pools = model_step(cur[:, None], pools, ln)
                ln = ln + 1
                key, kk = jax.random.split(key)
                new = pick(logits[:, -1].astype(jnp.float32),
                           kk).astype(jnp.int32)
                if eos_token_id is not None:
                    new = jnp.where(dn, jnp.asarray(eos_token_id,
                                                    new.dtype), new)
                    dn = dn | (new == eos_token_id)
                return (pools, ln, new, key, dn), new

            (pools, lens_, _, _, done_), toks = lax.scan(
                body, (pools, lens_, pending_, key0, done_), None,
                length=max_new_tokens)
            out = jnp.moveaxis(toks, 0, 1).astype(jnp.int64)
            return out, pools, lens_, done_

        nb, bs = pools[0][0].shape[0], pools[0][0].shape[1]
        sig = ("paged_decode", b, nb, bs, int(tables.shape[1]),
               int(max_new_tokens), float(temperature), int(top_k),
               float(top_p),
               None if eos_token_id is None else int(eos_token_id),
               str(cdt), "q8" if q8 else "full", "c8" if c8 else "fp",
               _mesh.mesh_axis_size("mp"))
        fn = self._gen_cache_get(
            sig, lambda: jax.jit(run, donate_argnums=(1,)))
        payload = tuple(qmap[i] if i in qmap else p._data
                        for i, p in enumerate(params)) if q8 else \
            tuple(p._data for p in params)
        toks, pools2, lens2, done2 = fn(payload, pools, tables, lens_arr,
                                        pending_arr, done_arr,
                                        jax.random.PRNGKey(seed))
        return Tensor(toks), pools2, lens2, done2

    def verify_paged(self, pools, block_tables, lens, pending, draft,
                     done, eos_token_id: int = None,
                     weight_dtype: str = None, cache_dtype: str = None):
        """One speculative-decode VERIFY step against the paged pool
        (ISSUE 11): score a [B, k] token window in ONE fixed-shape call
        through the ragged multi-token paged-attention primitive and
        apply the longest-accepted-prefix rule.

        The window per row is ``[pending, draft[0], ..., draft[k-2]]`` —
        each row's sampled-but-unwritten token followed by ``k - 1``
        drafted guesses (prompt-lookup from the prefix trie, or any other
        drafter). The call writes all k tokens' K/V at positions
        ``lens[b] + i`` (the suffix-prefill scatter: writes past a row's
        block budget land in the trash block), attends causally across
        the cached prefix + the window, and takes the greedy argmax at
        every position. Acceptance is DATA, not shape: draft token i is
        accepted iff it equals the chain token the target emits at window
        position i - 1, and the emitted row is the chain ``e`` with EOS
        forcing applied exactly like decode_paged's per-step masking — so
        greedy output is BIT-IDENTICAL per row to the non-speculative
        chain however many drafts hit or miss. Rejected-position KV
        writes are garbage BELOW the next window's start: every later
        window rewrites them before they become attendable, so no
        cleanup pass exists.

        Returns ``(emitted [B, k] int64, n_accept [B] int32, pools',
        done')``: row b emitted ``n_accept[b] + 1`` valid tokens
        (``emitted[b, :n_accept[b] + 1]``, the accepted drafts re-stated
        by the target plus the bonus token); its next pending token is
        ``emitted[b, n_accept[b]]`` and its cache frontier advanced by
        ``n_accept[b] + 1``. The pools are DONATED. One executable per
        window size k serves every accept/reject mix — tables / lens /
        pending / draft / done are all data inputs.

        Greedy only: the bit-exact acceptance rule IS argmax equality;
        sampled speculative decoding needs a rejection-sampling rule
        this engine does not implement."""
        import jax
        from ..jit.api import _swap_params, _trace_guard
        from ..core import autograd

        tables = jnp.asarray(
            block_tables._data if isinstance(block_tables, Tensor)
            else block_tables, jnp.int32)
        b = tables.shape[0]
        lens_arr = jnp.asarray(
            lens._data if isinstance(lens, Tensor) else lens, jnp.int32)
        pending_arr = jnp.asarray(
            pending._data if isinstance(pending, Tensor) else pending,
            jnp.int32)
        draft_arr = jnp.asarray(
            draft._data if isinstance(draft, Tensor) else draft, jnp.int32)
        if draft_arr.ndim != 2 or draft_arr.shape[0] != b:
            raise ValueError(f"draft must be [B, k-1]; got "
                             f"{draft_arr.shape} for batch {b}")
        k = int(draft_arr.shape[1]) + 1
        done_arr = jnp.asarray(
            done._data if isinstance(done, Tensor) else done, bool)
        params = list(self.parameters())
        cdt = self.gpt.wte.weight._data.dtype
        c8 = _check_pool_dtype(pools, cdt, cache_dtype)
        tag = "paged8" if c8 else "paged"
        n_pool = 4 if c8 else 2
        q8 = weight_dtype == "int8"
        qmap = self._decode_quantized_params() if q8 else {}
        expand = self._make_expand(q8, cdt)

        def run(pa, pools, tbl, lens_, pending_, draft_, done_):
            pa = _replicate_tree(pa)
            window = jnp.concatenate([pending_[:, None], draft_], axis=1)
            ex, pays = expand(pa)
            with _trace_guard(), _swap_params(params, ex), \
                    _q8_bind(params, pays), autograd.no_grad():
                # the suffix-prefill cache form: writes at lens + i,
                # attention across the pool — the [B, k] multi-token
                # primitive; `lens` rides both as the branch's lens slot
                # (unused for s > 1) and as the start offset
                caches = [(tag,) + tuple(Tensor(p) for p in layer) +
                          (Tensor(tbl), Tensor(lens_), Tensor(lens_))
                          for layer in pools]
                pos = lens_[:, None] + jnp.arange(k, dtype=jnp.int32)[None]
                logits, nc = self.forward(
                    Tensor(window), position_ids=Tensor(pos),
                    caches=caches)
            new_pools = [tuple(e._data for e in c[1:1 + n_pool])
                         for c in nc]
            raw = jnp.argmax(logits._data.astype(jnp.float32),
                             axis=-1).astype(jnp.int32)        # [B, k]
            if eos_token_id is None:
                e = raw
                match = (draft_ == raw[:, :-1]).astype(jnp.int32)
                n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                done_out = done_
            else:
                eos = jnp.asarray(eos_token_id, raw.dtype)
                # a row is "done" at window position i iff it was done on
                # entry or the chain emitted EOS strictly before i — the
                # sequential rule decode_paged applies per step, closed
                # into one cumulative form
                hit = (raw == eos).astype(jnp.int32)
                seen_before = jnp.cumsum(hit, axis=1) - hit
                done_i = done_[:, None] | (seen_before > 0)
                e = jnp.where(done_i, eos, raw)
                match = (draft_ == e[:, :-1]).astype(jnp.int32)
                n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                emitted = jnp.arange(k, dtype=jnp.int32)[None] <= \
                    n_acc[:, None]
                done_out = done_ | jnp.any((e == eos) & emitted, axis=1)
            return (e.astype(jnp.int64), n_acc.astype(jnp.int32),
                    new_pools, done_out)

        nb, bs = pools[0][0].shape[0], pools[0][0].shape[1]
        sig = ("paged_verify", b, k, nb, bs, int(tables.shape[1]),
               None if eos_token_id is None else int(eos_token_id),
               str(cdt), "q8" if q8 else "full", "c8" if c8 else "fp",
               _mesh.mesh_axis_size("mp"))
        fn = self._gen_cache_get(
            sig, lambda: jax.jit(run, donate_argnums=(1,)))
        payload = tuple(qmap[i] if i in qmap else p._data
                        for i, p in enumerate(params)) if q8 else \
            tuple(p._data for p in params)
        toks, n_acc, pools2, done2 = fn(payload, pools, tables, lens_arr,
                                        pending_arr, draft_arr, done_arr)
        return Tensor(toks), n_acc, pools2, done2

    def _make_expand(self, q8, cdt):
        """The shared mixed-payload expander (full arrays pass through;
        barrier'd int8 (codes, scale) pairs dequantize at use AND ride
        along for the int8-GEMM consumer hooks)."""
        from jax import lax

        def expand(pa):
            if not q8:
                return list(pa), [None] * len(pa)
            out, pays = [], []
            for v in pa:
                if isinstance(v, tuple):
                    qv, sv = lax.optimization_barrier(v)
                    out.append((qv.astype(jnp.float32) * sv).astype(cdt))
                    pays.append((qv, sv))
                else:
                    out.append(v)
                    pays.append(None)
            return out, pays
        return expand

    def _gen_cache_get(self, sig, build):
        """LRU-capped compiled-runner cache shared by every static-serving
        entry point (generate_static/_ragged, prefill/decode_static). A
        build here is a new serving executable — it feeds the process-wide
        jit cache-miss counter so StepMonitor (and the serving engine's
        steady-state guard) see serving compiles exactly like training
        recompiles."""
        import collections
        from ..jit.api import _note_cache_miss
        cache = getattr(self, "_gen_static_cache", None)
        if cache is None:
            cache = self._gen_static_cache = collections.OrderedDict()
        fn = cache.get(sig)
        if fn is None:
            _note_cache_miss()
            fn = cache[sig] = build()
            # 16 comfortably holds a serving engine's working set: one
            # prefill + one fresh-decode + one resume-decode executable
            # per chunk size (resume depth is a data input, not a sig key)
            while len(cache) > 16:
                cache.popitem(last=False)
        else:
            cache.move_to_end(sig)
        from ..jit.api import _maybe_wrap_lint_capture
        return _maybe_wrap_lint_capture(fn, sig)

    def generate_static_ragged(self, input_ids, prompt_lens,
                               max_new_tokens: int = 16,
                               temperature: float = 0.0, top_k: int = 0,
                               top_p: float = 1.0, max_len: int = None,
                               seed: int = 0, eos_token_id: int = None,
                               weight_dtype: str = None,
                               cache_dtype: str = None):
        """ONE compiled program for ANY prompt length (VERDICT r3 #7a).

        input_ids: [B, P_cap] prompts RIGHT-padded to a fixed cap; only
        rows < prompt_lens[b] are real. prompt_lens is a data INPUT of the
        compiled program, not part of its signature — a serving frontend
        with ragged prompts reuses one executable instead of recompiling
        per length (generate_static's behavior). Mechanism: prefill runs
        on the padded prompt; cache rows holding padded-garbage k/v are
        masked per batch row by static_cache_mask's ragged form; decode
        positions continue from each row's TRUE length so wpe lookups
        match an unpadded run exactly.

        Returns [B, P_cap + max_new_tokens]: each row is its padded prompt
        followed by its generated continuation.

        Reference anchor: fused_multi_transformer_op.cu serves its CacheKV
        workspace the same way — fixed buffers, per-sequence lengths."""
        import jax
        from jax import lax
        from ..jit.api import _swap_params, _trace_guard
        from ..core import autograd

        cfg = self.config
        ids = input_ids if isinstance(input_ids, Tensor) else Tensor(input_ids)
        if max_new_tokens <= 0:
            return ids
        b, p_cap = ids.shape
        lens_arr = _coerce_prompt_lens(prompt_lens, p_cap,
                                       "generate_static_ragged")
        L = int(max_len or (p_cap + max_new_tokens))
        assert L >= p_cap + max_new_tokens, "max_len too small"
        params = list(self.parameters())
        cdt = self.gpt.wte.weight._data.dtype
        nh, hd, nl = cfg.num_heads, cfg.head_dim, cfg.num_layers
        q8 = weight_dtype == "int8"
        c8 = _validate_cache_dtype(cache_dtype, cdt)
        qmap = self._decode_quantized_params() if q8 else {}
        # same weight-only int8 contract as generate_static (_make_expand)
        expand = self._make_expand(q8, cdt)

        def model_step(pa, tokens, caches, pos_ids):
            ex, pays = expand(pa)
            with _trace_guard(), _swap_params(params, ex), \
                    _q8_bind(params, pays), autograd.no_grad():
                # carry entries are flat tuples ending in the lens vector;
                # the forward's ragged element is the nested (lens, cap)
                logits, nc = self.forward(
                    Tensor(tokens), position_ids=Tensor(pos_ids),
                    caches=_wrap_ragged_caches(caches, p_cap))
            return logits._data, _unwrap_ragged_caches(nc)

        def pick(last, key):
            return sample_logits(last, key, temperature=temperature,
                                 top_k=top_k, top_p=top_p)

        def run(pa, prompt, lens, key0):
            caches = _make_static_caches(c8, nl, b, L, nh, hd, cdt,
                                         lens=lens)
            pos0 = jnp.broadcast_to(jnp.arange(p_cap, dtype=jnp.int32)[None],
                                    (b, p_cap))
            logits, caches = model_step(pa, prompt, caches, pos0)
            # next-token logits live at each row's LAST REAL position
            last = logits[jnp.arange(b), lens - 1].astype(jnp.float32)
            key0, k1 = jax.random.split(key0)
            nxt = pick(last, k1)
            done = (jnp.zeros((b,), bool) if eos_token_id is None
                    else nxt == eos_token_id)

            def body(carry, step):
                caches, cur, key, done = carry
                # cur is the (step)-th generated token (1-indexed), i.e. it
                # sits at sequence position lens + step - 1 in its row
                pos = (lens + step - 1)[:, None]
                logits, caches = model_step(pa, cur[:, None], caches, pos)
                key, kk = jax.random.split(key)
                new = pick(logits[:, -1].astype(jnp.float32), kk)
                if eos_token_id is not None:
                    new = jnp.where(done, jnp.asarray(eos_token_id,
                                                      new.dtype), new)
                    done = done | (new == eos_token_id)
                return (caches, new, key, done), new

            (_, _, _, _), toks = lax.scan(
                body, (caches, nxt, key0, done),
                jnp.arange(1, max_new_tokens, dtype=jnp.int32))
            gen = jnp.concatenate([nxt[:, None], jnp.moveaxis(toks, 0, 1)],
                                  axis=1)
            return jnp.concatenate([prompt.astype(jnp.int64),
                                    gen.astype(jnp.int64)], axis=1)

        # signature excludes the lengths: THE ragged-serving property
        sig = ("ragged", b, p_cap, int(max_new_tokens), L,
               float(temperature), int(top_k), float(top_p),
               None if eos_token_id is None else int(eos_token_id), str(cdt),
               "q8" if q8 else "full", "c8" if c8 else "cfull")
        fn = self._gen_cache_get(sig, lambda: jax.jit(run))
        payload = tuple(qmap[i] if i in qmap else p._data
                        for i, p in enumerate(params)) if q8 else \
            tuple(p._data for p in params)
        out = fn(payload, ids._data, lens_arr, jax.random.PRNGKey(seed))
        return Tensor(out)

    def generate(self, input_ids, max_new_tokens: int = 16,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int = None,
                 eos_token_id: int = None):
        """Greedy/temperature/top-k/top-p sampling with KV cache
        (reference: paddlenlp-style generate; cache semantics of
        MultiHeadAttention). seed=None (default) draws from the global
        paddle.seed stream — repeat calls sample fresh continuations, as
        the pre-top-k multinomial path did; pass an int for reproducible
        output (what generate_static defaults to for serving)."""
        b = input_ids.shape[0]
        # caches carry the MODEL dtype: f32 zero-length seeds would promote
        # every concatenated bf16 k/v to f32 (doubling decode cache
        # bandwidth) and silently de-pair the dtype story vs generate_static
        # (advisor r3 / VERDICT r3 weak #7)
        cdt = self.gpt.wte.weight._data.dtype.name
        caches = [(ops.zeros([b, 0, self.config.num_heads, self.config.head_dim],
                             dtype=cdt),
                   ops.zeros([b, 0, self.config.num_heads, self.config.head_dim],
                             dtype=cdt))
                  for _ in range(self.config.num_layers)]
        import jax
        from ..core import random as _random
        out = input_ids
        cur = input_ids
        key = jax.random.PRNGKey(seed) if seed is not None \
            else _random.split_key()
        import numpy as _np
        done = _np.zeros((b,), bool)
        for i in range(max_new_tokens):
            logits, caches = self.forward(cur, caches=caches)
            last = logits[:, -1]
            key, kk = jax.random.split(key)
            nxt = apply_op(
                "sample_logits",
                lambda a: sample_logits(a.astype(jnp.float32), kk,
                                        temperature=temperature, top_k=top_k,
                                        top_p=top_p)[:, None],
                [last])
            nxt = ops.cast(nxt, "int64")
            if eos_token_id is not None:
                # finished rows stay on EOS — masking stays on-device; the
                # only host read is the all-done check that drives `break`
                nxt = apply_op(
                    "eos_mask",
                    lambda a, d=jnp.asarray(done): jnp.where(
                        d[:, None], jnp.asarray(eos_token_id, a.dtype), a),
                    [nxt])
                done = done | (nxt.numpy()[:, 0] == eos_token_id)
            out = ops.concat([out, nxt], axis=1)
            cur = nxt
            if eos_token_id is not None and bool(done.all()):  # lint: allow(tracer-bool)
                break                           # eager path CAN stop early
        return out


def sample_logits(last, key, temperature=0.0, top_k=0, top_p=1.0):
    """Shared next-token selection on [B, V] f32 logits (pure jnp; used by
    both generate paths, eager and inside the compiled scan).

    Reference-era toolkit semantics (paddlenlp generation_utils
    TopKProcess/TopPProcess): temperature scales logits; top_k keeps the k
    best; top_p keeps the smallest prefix of the sorted distribution with
    cumulative probability >= p (always at least the best token)."""
    import jax
    if temperature <= 0.0:
        return jnp.argmax(last, axis=-1)
    logits = last / temperature
    neg = jnp.asarray(-1e30, logits.dtype)
    if top_k and top_k > 0:
        # clamp like the reference TopKProcess — serving knobs (e.g. 50)
        # must not abort on small vocabularies
        kth = jax.lax.top_k(logits, min(int(top_k), logits.shape[-1]))[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p < 1.0:
        sort_idx = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep ranks whose PRECEDING mass is < p; rank 0 is kept
        # unconditionally so top_p=0 degrades to argmax, not to token id 0
        keep_sorted = (cum - probs) < top_p
        keep_sorted = keep_sorted.at[..., 0].set(True)
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(logits.shape[0])[:, None], sort_idx].set(keep_sorted)
        logits = jnp.where(keep, logits, neg)
    return jax.random.categorical(key, logits, axis=-1)


def _masked_mean(per_tok, loss_mask):
    """Shared masked-mean reduction for both CE paths (criterion and the
    fused model.loss) — one definition, one epsilon convention."""
    if loss_mask is None:
        return ops.mean(per_tok)
    per_tok = per_tok * loss_mask
    return ops.sum(per_tok) / ops.maximum(
        ops.sum(loss_mask), ops.full([], 1e-8, loss_mask.dtype))


class GPTPretrainingCriterion(Layer):
    """Reference: PaddleNLP GPTPretrainingCriterion — masked mean CE over
    vocab-parallel logits (ParallelCrossEntropy analog)."""

    def __init__(self, config: Optional[GPTConfig] = None):
        super().__init__()
        self.ce = ParallelCrossEntropy()

    def forward(self, logits, labels, loss_mask=None, aux_loss=None):
        """For MoE configs pass the router load-balance loss explicitly:
        crit(model(ids), ids, aux_loss=model.gpt.last_aux_loss) — the
        criterion only sees logits and cannot recover it (model.loss()
        adds it automatically)."""
        loss = _masked_mean(ops.squeeze(self.ce(logits, labels), -1),
                            loss_mask)
        if aux_loss is not None:
            return loss + aux_loss
        return loss
