"""paddle.save / paddle.load — object checkpointing in the REFERENCE wire
format.

Reference: python/paddle/framework/io.py:637 (save), :879 (load) — a
`.pdparams`/`.pdopt` file is one pickle of the state dict with tensors
converted to raw numpy arrays, plus a "StructuredToParameterName@@" name
table (io.py:59 _build_saved_state_dict); under pickle protocol 2/3,
arrays over 2^30-1 bytes are split into "key@@.N" slices described by
"UnpackBigParamInfor@@" (fluid/io.py:1845 _unpack_saved_dict /
:1887 _pack_loaded_dict). Files produced here load in reference paddle and
vice versa — the first thing a migrating user does.

Nested non-state-dict objects (lists, scalars, nested dicts) pickle
recursively with tensors as numpy, matching the reference contract. Files
written by earlier paddle_tpu versions (sentinel-wrapped tensors) still
load. Large-scale sharded/async checkpoints live in
paddle_tpu.distributed.checkpoint (orbax-backed), the analog of the
reference's incubate dist_save.
"""
from __future__ import annotations

import math
import pickle

import numpy as np
import jax

from ..core.tensor import Tensor, Parameter

_SENTINEL = "__paddle_tpu_tensor__"          # legacy (pre-r4) wire format
_NAME_TABLE = "StructuredToParameterName@@"  # reference io.py:77
_UNPACK_INFO = "UnpackBigParamInfor@@"       # reference fluid/io.py:1878


def _to_numpy(v):
    if isinstance(v, Tensor):
        return np.asarray(v._data)
    if isinstance(v, jax.Array):
        return np.asarray(v)
    return None


def _pack(obj):
    """Tensors → raw numpy, recursively (reference: tensors pickle as
    their numpy values)."""
    arr = _to_numpy(obj)
    if arr is not None:
        return arr
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v) for v in obj)
    return obj


def _build_saved_state_dict(obj):
    """Reference io.py:59: top-level dict gains the structured→parameter
    name table (structured names ARE the parameter names here — one
    namespace, no auto-generated linear_0.w_0 aliases)."""
    packed = _pack(obj)
    if isinstance(packed, dict) and _NAME_TABLE not in packed:
        name_table = {k: k for k, v in packed.items()
                      if isinstance(v, np.ndarray)
                      and isinstance(obj.get(k), (Tensor, jax.Array))}
        if name_table:
            packed[_NAME_TABLE] = name_table
    return packed


def _unpack_saved_dict(saved, protocol):
    """Reference fluid/io.py:1845: protocols 2/3 cannot pickle >4GB
    objects — split big arrays into flat "key@@.N" slices."""
    if not (1 < protocol < 4) or not isinstance(saved, dict):
        return saved
    unpack_infor = {}
    out = dict(saved)
    for key, value in saved.items():
        if not isinstance(value, np.ndarray):
            continue
        max_elems = int((2 ** 30 - 1) / value.dtype.itemsize)
        num = int(np.prod(value.shape))
        if num <= max_elems:
            continue
        unpack_infor[key] = {"OriginShape": value.shape, "slices": []}
        flat = value.flatten()
        out.pop(key)
        for i in range(int(math.ceil(num / max_elems))):
            part = f"{key}@@.{i}"
            unpack_infor[key]["slices"].append(part)
            out[part] = flat[i * max_elems:(i + 1) * max_elems]
    if unpack_infor:
        out[_UNPACK_INFO] = unpack_infor
    return out


def _pack_loaded_dict(loaded):
    """Reference fluid/io.py:1887: reassemble "key@@.N" slices."""
    if isinstance(loaded, dict) and _UNPACK_INFO in loaded:
        removes = []
        for key, info in loaded[_UNPACK_INFO].items():
            slices = [loaded[p] for p in info["slices"]]
            loaded[key] = np.concatenate(slices).reshape(info["OriginShape"])
            removes += info["slices"]
        for k in removes:
            loaded.pop(k)
        loaded.pop(_UNPACK_INFO)
    return loaded


def _unpack(obj, return_numpy=False):
    if isinstance(obj, np.ndarray):
        return obj if return_numpy else Tensor(obj)
    if isinstance(obj, dict):
        if obj.get(_SENTINEL):          # legacy paddle_tpu wire format
            if return_numpy:
                return obj["data"]
            if obj["param"]:
                return Parameter(obj["data"],
                                 trainable=not obj["stop_gradient"])
            return Tensor(obj["data"], stop_gradient=obj["stop_gradient"])
        return {k: _unpack(v, return_numpy) for k, v in obj.items()
                if k != _NAME_TABLE} | (
                    {_NAME_TABLE: obj[_NAME_TABLE]}
                    if _NAME_TABLE in obj else {})
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol: int = 4):
    """Serialize in the reference .pdparams/.pdopt wire format.

    The file write is ATOMIC (tmp-then-rename, resilience.atomic_writer):
    a kill at any byte — including a pickling error halfway through a
    multi-GB state dict — leaves either the previous `path` contents or
    the complete new ones, never a truncated pickle. The reference (and
    this repo pre-r12) wrote the target path directly, so a crash during
    a periodic `paddle.save` destroyed the very checkpoint being
    refreshed."""
    if not (1 < protocol < 5):
        raise ValueError(f"protocol must be 2..4, got {protocol}")
    if isinstance(obj, dict):
        packed = _build_saved_state_dict(obj)
    else:
        packed = _pack(obj)
    packed = _unpack_saved_dict(packed, protocol)
    if hasattr(path, "write"):
        pickle.dump(packed, path, protocol=protocol)
        return
    from ..resilience.checkpoint import atomic_writer
    with atomic_writer(str(path)) as f:
        pickle.dump(packed, f, protocol=protocol)


def load(path, return_numpy: bool = False, **config):
    if hasattr(path, "read"):
        packed = pickle.load(path)
    else:
        with open(path, "rb") as f:
            packed = pickle.load(f)
    packed = _pack_loaded_dict(packed)
    return _unpack(packed, return_numpy=return_numpy)
