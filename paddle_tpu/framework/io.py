"""paddle.save / paddle.load — object checkpointing.

Reference: python/paddle/framework/io.py:637 (save), :879 (load) — pickles
nested state_dicts with tensors converted to numpy. We keep the same contract
(nested dict/list of Tensors + python scalars, file or path-like), storing
tensors as numpy inside a single pickle; large-scale sharded/async checkpoints
live in paddle_tpu.distributed.checkpoint (orbax-backed), the analog of the
reference's incubate dist_save (incubate/distributed/utils/io/dist_save.py).
"""
from __future__ import annotations

import io as _io
import os
import pickle

import numpy as np
import jax

from ..core.tensor import Tensor, Parameter


_SENTINEL = "__paddle_tpu_tensor__"


def _pack(obj):
    if isinstance(obj, Tensor):
        return {_SENTINEL: True, "data": np.asarray(obj._data),
                "stop_gradient": obj.stop_gradient,
                "param": isinstance(obj, Parameter)}
    if isinstance(obj, jax.Array):
        return {_SENTINEL: True, "data": np.asarray(obj), "stop_gradient": True,
                "param": False}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get(_SENTINEL):
            if return_numpy:
                return obj["data"]
            if obj["param"]:
                return Parameter(obj["data"], trainable=not obj["stop_gradient"])
            return Tensor(obj["data"], stop_gradient=obj["stop_gradient"])
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol: int = 4):
    """Serialize a (possibly nested) object containing Tensors."""
    packed = _pack(obj)
    if hasattr(path, "write"):
        pickle.dump(packed, path, protocol=protocol)
        return
    d = os.path.dirname(str(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(packed, f, protocol=protocol)


def load(path, return_numpy: bool = False, **config):
    if hasattr(path, "read"):
        packed = pickle.load(path)
    else:
        with open(path, "rb") as f:
            packed = pickle.load(f)
    return _unpack(packed, return_numpy=return_numpy)
