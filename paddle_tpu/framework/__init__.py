from . import io  # noqa: F401
from .io import save, load  # noqa: F401
