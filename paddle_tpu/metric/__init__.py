"""paddle.metric analog (reference: python/paddle/metric/metrics.py:33-601)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return np.asarray(x._data) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__.lower()

    def name(self):
        return self._name

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    """Reference: metric/metrics.py:187 Accuracy (top-k)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = topk_idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        for i, k in enumerate(self.topk):
            num = c[..., :k].sum()
            self.total[i] += num
            self.count[i] += c.shape[0] if c.ndim > 1 else 1
        acc = self.total / np.maximum(self.count, 1)
        return acc[0] if len(self.topk) == 1 else acc

    def accumulate(self):
        acc = self.total / np.maximum(self.count, 1)
        return float(acc[0]) if len(self.topk) == 1 else acc.tolist()


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Auc(Metric):
    """Reference: metric/metrics.py Auc — thresholded ROC AUC accumulator."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = _np(labels).reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(np.int64), self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # integrate trapezoid over thresholds descending
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapz(tpr, fpr))


def accuracy(input, label, k=1):  # noqa: A002
    """Functional accuracy (reference: paddle.metric.accuracy)."""
    pred = _np(input)
    lab = _np(label)
    if lab.ndim == pred.ndim:
        lab = lab.squeeze(-1)
    topk = np.argsort(-pred, axis=-1)[..., :k]
    correct = (topk == lab[..., None]).any(axis=-1)
    return Tensor(np.asarray(correct.mean(), dtype=np.float32))
