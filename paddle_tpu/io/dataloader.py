"""DataLoader (reference: python/paddle/io/dataloader/* and
fluid/reader.py DataLoader; worker model in dataloader_iter.py:370).

Design: collate on host into numpy, optionally prefetch with a background
thread pool (replaces the reference's forked worker processes + shared-memory
queue: TPU input pipelines are bandwidth-bound on host→device transfer, which
jax overlaps automatically once batches are ready ahead of time).
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ..core.tensor import Tensor, to_tensor
from .dataset import IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return to_tensor(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return batch
    return to_tensor(np.asarray(batch))


def _numpy_collate(batch):
    """Worker-side collate: numpy only (no jax in worker processes)."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return [_numpy_collate(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: _numpy_collate([d[k] for d in batch]) for k in sample}
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    return np.asarray(batch)


def _tree_to_tensor(tree):
    if isinstance(tree, list):
        return [_tree_to_tensor(t) for t in tree]
    if isinstance(tree, dict):
        return {k: _tree_to_tensor(v) for k, v in tree.items()}
    if isinstance(tree, np.ndarray):
        return to_tensor(tree)
    return tree


_worker_state = {}


def _worker_init(dataset, collate_in_worker, worker_init_fn, counter,
                 num_workers):
    _worker_state["dataset"] = dataset
    _worker_state["collate"] = collate_in_worker
    # worker id contract: 0..num_workers-1 (reference worker_init_fn(worker_id)).
    # modulo keeps respawned replacements (Pool repopulates after a worker
    # death) inside the contract range
    with counter.get_lock():
        wid = counter.value % num_workers
        counter.value += 1
    _worker_state["worker_id"] = wid
    _worker_state["num_workers"] = num_workers
    if worker_init_fn is not None:
        worker_init_fn(wid)


def _worker_fetch(indices):
    ds = _worker_state["dataset"]
    samples = [ds[i] for i in indices]
    if _worker_state["collate"]:
        return _numpy_collate(samples)
    return samples


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self._custom_collate = collate_fn is not None
        self.collate_fn = collate_fn or default_collate_fn
        self.worker_init_fn = worker_init_fn
        self.num_workers = num_workers
        self.persistent_workers = persistent_workers
        self._pool = None
        self.prefetch_factor = max(2, prefetch_factor)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.return_list = return_list
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __del__(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            try:
                pool.terminate()
                pool.join()
            except Exception:
                pass

    def _batches(self):
        if self._iterable_mode:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._batches()
            return
        if not self._iterable_mode:
            # true multi-process path (reference: dataloader_iter.py:370
            # _DataLoaderIterMultiProcess with shared-memory workers): worker
            # processes run __getitem__+collate off the GIL; pool.imap keeps
            # batch order. Falls back to the thread path if the dataset
            # doesn't pickle.
            gen = self._process_worker_iter()
            if gen is not None:
                yield from gen
                return
        # background prefetch thread (pipeline host work with device compute)
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_factor * max(self.num_workers, 1))
        _END = object()
        err = []
        stop = threading.Event()

        def _put(item):
            # bounded put that gives up when the consumer abandoned iteration
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for b in self._batches():
                    if not _put(b):
                        return
            except BaseException as e:  # surface worker errors in the consumer
                err.append(e)
            finally:
                _put(_END)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                yield item
        finally:
            stop.set()  # unblock the producer if the consumer broke early
        if err:
            raise err[0]

    def _process_worker_iter(self):
        """Build the process-pool batch iterator, or None if unpicklable."""
        import multiprocessing as mp
        import pickle
        # workers must NOT touch jax (each would claim the device): they
        # fetch samples and collate to NUMPY; the parent converts to Tensor
        # (default collate) or runs the user's collate_fn on raw samples —
        # so a custom collate_fn never needs to pickle
        collate_in_worker = not self._custom_collate
        try:
            pickle.dumps(self.dataset)
        except Exception:
            return None
        pool = getattr(self, "_pool", None)
        if pool is None:
            ctx = mp.get_context("spawn")
            try:
                counter = ctx.Value("i", 0)
                pool = ctx.Pool(self.num_workers, initializer=_worker_init,
                                initargs=(self.dataset, collate_in_worker,
                                          self.worker_init_fn, counter,
                                          self.num_workers))
            except Exception:
                return None
            if self.persistent_workers:
                self._pool = pool

        def gen():
            try:
                indices_list = list(self.batch_sampler)
                for payload in pool.imap(_worker_fetch, indices_list,
                                         chunksize=1):
                    if collate_in_worker:
                        yield _tree_to_tensor(payload)
                    else:
                        yield self.collate_fn(payload)
            finally:
                if not self.persistent_workers:
                    pool.terminate()
                    pool.join()
        return gen()


class WorkerInfo:
    """reference: io.get_worker_info — id/num_workers/dataset of the calling
    worker; None in the main process."""

    def __init__(self, id, num_workers, dataset):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, num_workers={self.num_workers})")


def get_worker_info():
    if "worker_id" not in _worker_state:
        return None
    return WorkerInfo(_worker_state["worker_id"],
                      _worker_state.get("num_workers", 1),
                      _worker_state.get("dataset"))
