"""DataLoader (reference: python/paddle/io/dataloader/* and
fluid/reader.py DataLoader; worker model in dataloader_iter.py:370).

Design: collate on host into numpy, optionally prefetch with a background
thread pool (replaces the reference's forked worker processes + shared-memory
queue: TPU input pipelines are bandwidth-bound on host→device transfer, which
jax overlaps automatically once batches are ready ahead of time).
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ..core.tensor import Tensor, to_tensor
from .dataset import IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return to_tensor(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return batch
    return to_tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(2, prefetch_factor)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.return_list = return_list
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _batches(self):
        if self._iterable_mode:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._batches()
            return
        # background prefetch thread (pipeline host work with device compute)
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_factor * max(self.num_workers, 1))
        _END = object()
        err = []
        stop = threading.Event()

        def _put(item):
            # bounded put that gives up when the consumer abandoned iteration
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for b in self._batches():
                    if not _put(b):
                        return
            except BaseException as e:  # surface worker errors in the consumer
                err.append(e)
            finally:
                _put(_END)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                yield item
        finally:
            stop.set()  # unblock the producer if the consumer broke early
        if err:
            raise err[0]
