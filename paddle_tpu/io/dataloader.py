"""DataLoader (reference: python/paddle/io/dataloader/* and
fluid/reader.py DataLoader; worker model in dataloader_iter.py:370).

Design: collate on host into numpy, optionally prefetch with a background
thread pool (replaces the reference's forked worker processes + shared-memory
queue: TPU input pipelines are bandwidth-bound on host→device transfer, which
jax overlaps automatically once batches are ready ahead of time).
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..profiler.timeline import current as _timeline_current
from .dataset import IterableDataset
from .sampler import BatchSampler, DistributedBatchSampler


class DataLoaderTimeoutError(TimeoutError):
    """`DataLoader(timeout=...)` expired while waiting on a worker. The
    message names the stalled worker; `.worker` carries it structured
    (`"prefetch-thread"` or `"process-pool"`), `.waited_s` how long the
    consumer blocked."""

    def __init__(self, message: str, *, worker: str, waited_s: float):
        self.worker = worker
        self.waited_s = waited_s
        super().__init__(message)


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return to_tensor(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return batch
    return to_tensor(np.asarray(batch))


def _numpy_collate(batch):
    """Worker-side collate: numpy only (no jax in worker processes)."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return [_numpy_collate(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: _numpy_collate([d[k] for d in batch]) for k in sample}
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    return np.asarray(batch)


def _tree_to_tensor(tree):
    if isinstance(tree, list):
        return [_tree_to_tensor(t) for t in tree]
    if isinstance(tree, dict):
        return {k: _tree_to_tensor(v) for k, v in tree.items()}
    if isinstance(tree, np.ndarray):
        return to_tensor(tree)
    return tree


_worker_state = {}


def _worker_init(dataset, collate_in_worker, worker_init_fn, counter,
                 num_workers):
    _worker_state["dataset"] = dataset
    _worker_state["collate"] = collate_in_worker
    # worker id contract: 0..num_workers-1 (reference worker_init_fn(worker_id)).
    # modulo keeps respawned replacements (Pool repopulates after a worker
    # death) inside the contract range
    with counter.get_lock():
        wid = counter.value % num_workers
        counter.value += 1
    _worker_state["worker_id"] = wid
    _worker_state["num_workers"] = num_workers
    if worker_init_fn is not None:
        worker_init_fn(wid)


def _worker_fetch(indices):
    ds = _worker_state["dataset"]
    samples = [ds[i] for i in indices]
    if _worker_state["collate"]:
        return _numpy_collate(samples)
    return samples


class SeededBatchSampler(BatchSampler):
    """Deterministically shuffled batches: epoch ``e``'s ordering is
    ``RandomState(seed + e).permutation`` (the DistributedBatchSampler
    idiom, minus the rank sharding). The point is RESUMABILITY: a
    (seed, epoch, batch_idx) cursor fully determines the remaining batch
    stream, so a restarted job sees exactly the batches the interrupted
    one would have — the dataloader leg of bit-exact resume
    (resilience.TrainState)."""

    def __init__(self, dataset=None, batch_size=1, shuffle=False,
                 drop_last=False, seed=0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = int(seed)
        self.epoch = 0

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            indices = np.random.RandomState(
                self.seed + self.epoch).permutation(n).tolist()
        else:
            indices = list(range(n))
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False, seed=None):
        self.dataset = dataset
        self._custom_collate = collate_fn is not None
        self.collate_fn = collate_fn or default_collate_fn
        self.worker_init_fn = worker_init_fn
        self.num_workers = num_workers
        self.persistent_workers = persistent_workers
        self._pool = None
        self.prefetch_factor = max(2, prefetch_factor)
        # timeout applies to the WORKER paths: how long __next__ may block
        # on an empty prefetch buffer / a pool fetch before raising
        # DataLoaderTimeoutError (0 = wait forever, reference semantics)
        self.timeout = float(timeout or 0)
        # goodput accounting (profiler.timeline): explicit recorder, or
        # the process-wide installed one. input-stall seconds accumulate
        # here either way — `stall_stats()` is the cheap live view
        self.timeline = None
        self._consumer_wait_s = 0.0   # __next__ blocked on empty buffer
        self._producer_wait_s = 0.0   # prefetch thread blocked on full one
        self._stalled_batches = 0     # batches the consumer waited for
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.return_list = return_list
        if seed is not None and int(seed) < 0:
            # -1 is the cursor's "no seed" sentinel — a negative seed
            # would record an unreplayable-looking cursor
            raise ValueError(f"seed must be >= 0, got {seed}")
        self.seed = seed
        self._shuffle = bool(shuffle)
        # resumable cursor: epoch / batches-handed-out-this-epoch / pending
        # fast-forward (set by set_state_dict, consumed by the next iter)
        self._epoch = 0
        self._batch_idx = 0
        self._skip = 0
        self._pending_resume = False
        # only a sampler the loader built itself gets its epoch driven by
        # the loader's resume cursor — a user-provided batch_sampler (the
        # DistributedBatchSampler idiom) manages set_epoch itself and
        # must not be clobbered from _epoch
        self._owns_sampler = batch_sampler is None
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif seed is not None:
            self.batch_sampler = SeededBatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last, seed=seed)
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _cursor_seed(self) -> int:
        """The deterministic-order source: the loader's own seed=, or a
        seed-carrying user sampler (SeededBatchSampler /
        DistributedBatchSampler idiom). -1 = no seed anywhere."""
        if self.seed is not None:
            return int(self.seed)
        s = getattr(self.batch_sampler, "seed", None)
        return int(s) if s is not None else -1

    def _epoch_ordered(self) -> bool:
        """True when an UNSEEDED sampler's shuffle order is nonetheless a
        pure function of the epoch — DistributedBatchSampler permutes
        with RandomState(epoch) — so the cursor can replay it without a
        seed (__iter__ drives set_epoch on the resume iteration)."""
        return isinstance(self.batch_sampler, DistributedBatchSampler)

    def _cursor_geometry(self):
        """(batch_size, drop_last) actually in force — from the sampler
        on the map-style path, from the loader in iterable mode."""
        src = self if self._iterable_mode else self.batch_sampler
        bs = getattr(src, "batch_size", None)
        return (int(bs) if bs is not None else -1,
                bool(getattr(src, "drop_last", False)))

    # -- input-stall accounting (profiler.timeline `input_wait`) --------
    def _tl(self):
        return self.timeline if self.timeline is not None \
            else _timeline_current()

    def stall_stats(self) -> dict:
        """Cumulative input-pipeline stall split across this loader's
        life: `consumer_wait_s` is TRUE input-stall time (the training
        loop blocked on an empty prefetch buffer — badput, recorded as
        `input_wait` spans when a timeline recorder is installed);
        `producer_wait_s` is the prefetch thread blocked on a FULL
        buffer (the healthy state: input runs ahead of compute — it is
        overlap headroom, not badput, so it is a counter here and never
        a span)."""
        return {"consumer_wait_s": self._consumer_wait_s,
                "producer_wait_s": self._producer_wait_s,
                "stalled_batches": self._stalled_batches}

    # -- resumable cursor (resilience.TrainState "loader" slot) ---------
    def state_dict(self) -> dict:
        """(epoch, batch_idx, seed) cursor. batch_idx counts batches
        already handed out this epoch, so a snapshot taken while the
        trainer processes batch k records k+1 — the next batch a resumed
        run must see. Deterministic resume additionally needs a
        deterministic order: construct with ``seed=`` (or a seeded
        sampler); a plain shuffle=True loader draws from the global
        numpy RNG and cannot replay its epoch order."""
        bs, dl = self._cursor_geometry()
        return {"epoch": self._epoch, "batch_idx": self._batch_idx,
                "seed": self._cursor_seed(),
                "shuffle": bool(getattr(self.batch_sampler, "shuffle",
                                        self._shuffle)),
                "epoch_ordered": self._epoch_ordered(),
                "batch_size": bs, "drop_last": dl}

    def set_state_dict(self, state: dict):
        # validate BEFORE touching the cursor: a rejected restore must
        # leave the loader exactly as it was (a caller that catches the
        # error and trains fresh must not inherit an armed fast-forward)
        saved = int(state.get("seed", -1))
        here = self._cursor_seed()
        if saved != -1 and saved != here:
            # seed=None counts as a mismatch too: a plain shuffle=True
            # loader draws from the global numpy RNG and cannot replay
            # the recorded order
            raise ValueError(
                f"dataloader cursor was recorded with seed={saved} but "
                f"this loader has seed={self.seed}: the shuffle orders "
                f"differ, a resume would silently train on a different "
                f"batch stream")
        if saved == -1 and state.get("shuffle") and \
                not (state.get("epoch_ordered") and self._epoch_ordered()):
            # recorded from a shuffle=True loader with NO seed: the
            # original permutation came from the global numpy RNG and is
            # gone — fast-forwarding into a fresh draw would silently
            # train on a different batch stream. Exception: an
            # epoch-ordered sampler (DistributedBatchSampler) permutes
            # from RandomState(epoch) — deterministic without a seed —
            # provided the resuming loader uses one too.
            raise ValueError(
                "dataloader cursor was recorded from a shuffle=True "
                "loader without seed=: its epoch order cannot be "
                "replayed. Construct the training loader with seed= to "
                "make the stream resumable")
        rec_shuffle = state.get("shuffle")
        here_shuffle = bool(getattr(self.batch_sampler, "shuffle",
                                    self._shuffle))
        if rec_shuffle is not None and bool(rec_shuffle) != here_shuffle:
            # matching seeds don't help if one side shuffles and the
            # other is sequential — the epoch orders still differ
            raise ValueError(
                f"dataloader cursor was recorded with "
                f"shuffle={bool(rec_shuffle)} but this loader has "
                f"shuffle={here_shuffle}: the epoch orders differ, a "
                f"resume would silently train on a different batch "
                f"stream")
        here_bs, here_dl = self._cursor_geometry()
        rec_bs = state.get("batch_size")
        rec_dl = state.get("drop_last")
        if rec_bs is not None and int(rec_bs) != -1 and here_bs != -1 and \
                (int(rec_bs) != here_bs or
                 (rec_dl is not None and bool(rec_dl) != here_dl)):
            # batch_idx counts BATCHES: fast-forwarding k batches of a
            # different size lands on a different sample offset, so the
            # resumed stream silently diverges even with matching seeds
            raise ValueError(
                f"dataloader cursor was recorded with batch_size="
                f"{int(rec_bs)}, drop_last={bool(rec_dl)} but this "
                f"loader has batch_size={here_bs}, drop_last={here_dl}: "
                f"the batch boundaries differ, a resume would silently "
                f"train on a different batch stream")
        self._epoch = int(state.get("epoch", 0))
        self._batch_idx = int(state.get("batch_idx", 0))
        self._skip = self._batch_idx
        self._pending_resume = True
        return self

    def __del__(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            try:
                pool.terminate()
                pool.join()
            except Exception:
                pass

    def _batches(self, skip: int = 0):
        if self._iterable_mode:
            batch = []
            n_out = 0
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    n_out += 1
                    if n_out > skip:      # fast-forward consumes items,
                        yield self.collate_fn(batch)  # skips collation
                    batch = []
            if batch and not self.drop_last and n_out >= skip:
                yield self.collate_fn(batch)
        else:
            for i, indices in enumerate(self.batch_sampler):
                if i < skip:   # resume fast-forward: sampler indices only,
                    continue   # the dataset is never touched for them
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        """One epoch. A pending resume cursor (set_state_dict) fast-
        forwards `batch_idx` batches first — the sampler's index stream
        advances (keeping the epoch order aligned) but skipped batches
        are neither fetched nor collated on the map-style path."""
        if self.batch_sampler is not None and \
                hasattr(self.batch_sampler, "set_epoch") and \
                (self._owns_sampler or self._pending_resume):
            # owned samplers: the loader drives the epoch every iter. A
            # USER sampler manages set_epoch itself — except for the one
            # iteration that replays a restored cursor, where the skip
            # must fast-forward through the RECORDED epoch's permutation,
            # not whatever epoch the fresh sampler happens to hold.
            self.batch_sampler.set_epoch(self._epoch)
        self._pending_resume = False
        skip, self._skip = self._skip, 0
        self._batch_idx = skip
        for b in self._iter_impl(skip):
            self._batch_idx += 1
            yield b
        self._epoch += 1
        self._batch_idx = 0

    def _iter_impl(self, skip: int = 0):
        if self.num_workers == 0:
            tl = self._tl()
            if tl is None:
                yield from self._batches(skip)
                return
            # synchronous path under goodput accounting: every
            # fetch+collate runs ON the training thread and blocks it —
            # the whole fetch is attributed as `input_wait`
            # (split="sync"; there is no buffer whose emptiness to
            # measure)
            it = self._batches(skip)
            while True:
                t0 = tl.now()
                try:
                    b = next(it)
                except StopIteration:
                    return
                tl.record("input_wait", t0, tl.now(), split="sync")
                yield b
            return
        if not self._iterable_mode:
            # true multi-process path (reference: dataloader_iter.py:370
            # _DataLoaderIterMultiProcess with shared-memory workers): worker
            # processes run __getitem__+collate off the GIL; pool.imap keeps
            # batch order. Falls back to the thread path if the dataset
            # doesn't pickle.
            gen = self._process_worker_iter(skip)
            if gen is not None:
                yield from gen
                return
        # background prefetch thread (pipeline host work with device compute)
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_factor * max(self.num_workers, 1))
        _END = object()
        err = []
        stop = threading.Event()
        timeout = self.timeout

        def _put(item):
            # bounded put that gives up when the consumer abandoned
            # iteration (check BEFORE the fast path: once stop is set,
            # the producer must not keep fetching batches into the free
            # queue slots). Time blocked on a FULL queue is
            # producer-wait: input running AHEAD of compute — the
            # healthy half of the stall split, counted but never a
            # badput span.
            if stop.is_set():
                return False
            try:
                q.put_nowait(item)
                return True
            except queue.Full:
                pass
            w0 = time.monotonic()
            try:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        return True
                    except queue.Full:
                        continue
                return False
            finally:
                self._producer_wait_s += time.monotonic() - w0

        def producer():
            try:
                for b in self._batches(skip):
                    if not _put(b):
                        return
            except BaseException as e:  # surface worker errors in the consumer
                err.append(e)
            finally:
                _put(_END)

        def blocking_get():
            # EMPTY buffer: the training loop is now stalled on input —
            # the true `input_wait` badput. This wait is also where
            # `timeout=` is enforced (it was accepted-but-ignored on
            # this path before): a producer stuck in __getitem__ past
            # the deadline raises a named error instead of hanging the
            # job forever.
            tl = self._tl()
            w0 = time.monotonic()
            t0 = tl.now() if tl is not None else None
            while True:
                try:
                    item = q.get(timeout=0.05)
                    break
                except queue.Empty:
                    waited = time.monotonic() - w0
                    if timeout > 0 and waited >= timeout:
                        self._consumer_wait_s += waited
                        self._stalled_batches += 1
                        if tl is not None:
                            tl.record("input_wait", t0, tl.now(),
                                      split="producer", timed_out=True)
                        stop.set()
                        raise DataLoaderTimeoutError(
                            f"DataLoader timed out after {waited:.2f}s "
                            f"(timeout={timeout}s) waiting on the "
                            f"prefetch-thread worker (num_workers="
                            f"{self.num_workers}): the producer is "
                            f"stalled inside dataset __getitem__/collate "
                            f"and the buffer stayed empty",
                            worker="prefetch-thread", waited_s=waited)
            if item is not _END:
                # waiting out the end-of-epoch sentinel is not an input
                # stall — no batch was late, the epoch was just over
                self._consumer_wait_s += time.monotonic() - w0
                self._stalled_batches += 1
                if tl is not None:
                    tl.record("input_wait", t0, tl.now(), split="producer")
            return item

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                try:
                    # warm buffer: no wait, no span — steady-state input
                    # that keeps ahead of compute must measure ≈0 stall
                    item = q.get_nowait()
                except queue.Empty:
                    item = blocking_get()
                if item is _END:
                    break
                yield item
        finally:
            stop.set()  # unblock the producer if the consumer broke early
        if err:
            raise err[0]

    def _process_worker_iter(self, skip: int = 0):
        """Build the process-pool batch iterator, or None if unpicklable."""
        import multiprocessing as mp
        import pickle
        # workers must NOT touch jax (each would claim the device): they
        # fetch samples and collate to NUMPY; the parent converts to Tensor
        # (default collate) or runs the user's collate_fn on raw samples —
        # so a custom collate_fn never needs to pickle
        collate_in_worker = not self._custom_collate
        try:
            pickle.dumps(self.dataset)
        except Exception:
            return None
        pool = getattr(self, "_pool", None)
        if pool is None:
            ctx = mp.get_context("spawn")
            try:
                counter = ctx.Value("i", 0)
                pool = ctx.Pool(self.num_workers, initializer=_worker_init,
                                initargs=(self.dataset, collate_in_worker,
                                          self.worker_init_fn, counter,
                                          self.num_workers))
            except Exception:
                return None
            if self.persistent_workers:
                self._pool = pool

        def gen():
            timeout = self.timeout
            try:
                indices_list = list(self.batch_sampler)[skip:]
                it = pool.imap(_worker_fetch, indices_list, chunksize=1)
                while True:
                    tl = self._tl()
                    w0 = time.monotonic()
                    t0 = tl.now() if tl is not None else None
                    try:
                        # IMapIterator.next(timeout) is how `timeout=`
                        # reaches the pool path — a worker stuck in
                        # __getitem__ raises instead of hanging the job
                        payload = it.next(timeout) if timeout > 0 \
                            else next(it)
                    except StopIteration:
                        break
                    except mp.TimeoutError:
                        waited = time.monotonic() - w0
                        self._consumer_wait_s += waited
                        self._stalled_batches += 1
                        if tl is not None:
                            tl.record("input_wait", t0, tl.now(),
                                      split="producer", timed_out=True)
                        raise DataLoaderTimeoutError(
                            f"DataLoader timed out after {waited:.2f}s "
                            f"(timeout={timeout}s) waiting on a "
                            f"process-pool worker (num_workers="
                            f"{self.num_workers}): a worker is stalled "
                            f"inside dataset __getitem__",
                            worker="process-pool", waited_s=waited)
                    waited = time.monotonic() - w0
                    if waited > 1e-3:   # warm pool: sub-ms next() is not
                        self._consumer_wait_s += waited     # a stall
                        self._stalled_batches += 1
                        if tl is not None:
                            tl.record("input_wait", t0, tl.now(),
                                      split="producer")
                    if collate_in_worker:
                        yield _tree_to_tensor(payload)
                    else:
                        yield self.collate_fn(payload)
            finally:
                if not self.persistent_workers:
                    pool.terminate()
                    pool.join()
        return gen()


class WorkerInfo:
    """reference: io.get_worker_info — id/num_workers/dataset of the calling
    worker; None in the main process."""

    def __init__(self, id, num_workers, dataset):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, num_workers={self.num_workers})")


def get_worker_info():
    if "worker_id" not in _worker_state:
        return None
    return WorkerInfo(_worker_state["worker_id"],
                      _worker_state.get("num_workers", 1),
                      _worker_state.get("dataset"))
