"""ctypes bindings for the native data-pipeline core (native/src/
data_pipeline.cc) — C++ blocking queue + mmap record readers.

Reference analog (SURVEY §2.1 "Data pipeline (C++)"): framework/
data_feed.cc readers + BlockingQueue feeding training threads without
holding the GIL, and imperative/data_loader.cc. The .so builds on first use
with g++ (no pybind11 in this image — plain C ABI via ctypes); everything
degrades gracefully to the pure-Python DataLoader when a toolchain is
unavailable.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import struct
import subprocess
import threading
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "src", "data_pipeline.cc")
_LIB_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_LIB_DIR, "libptnative.so")

_lib = None
_lib_err: Optional[str] = None
_build_lock = threading.Lock()


def _sources():
    src_dir = os.path.dirname(_SRC)
    try:
        return sorted(os.path.join(src_dir, f) for f in os.listdir(src_dir)
                      if f.endswith(".cc")
                      and f != "predictor_capi.cc")  # own lib (needs libpython)
    except OSError:
        return [_SRC]


_INFER_LIB = os.path.join(_LIB_DIR, "libptinfer.so")


def build_infer_capi() -> Optional[str]:
    """Build the C inference ABI (native/src/predictor_capi.cc →
    libptinfer.so; header native/include/pt_inference_api.h). Separate from
    libptnative because it embeds CPython. Returns the .so path, or None
    with the error recorded (same contract as load_native)."""
    import sysconfig
    src = os.path.join(os.path.dirname(_SRC), "predictor_capi.cc")
    os.makedirs(_LIB_DIR, exist_ok=True)
    if os.path.exists(_INFER_LIB) and \
            os.path.getmtime(src) <= os.path.getmtime(_INFER_LIB):
        return _INFER_LIB
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
    ver = sysconfig.get_config_var("LDVERSION") or "3"
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           f"-I{inc}", src, f"-L{libdir}", f"-lpython{ver}",
           f"-Wl,-rpath,{libdir}", "-o", _INFER_LIB]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if r.returncode != 0:
        import sys
        sys.stderr.write(f"build_infer_capi failed: {r.stderr[-1500:]}\n")
        return None
    return _INFER_LIB


def _build() -> Optional[str]:
    os.makedirs(_LIB_DIR, exist_ok=True)
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           *_sources(), "-o", _LIB]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"toolchain unavailable: {e}"
    if r.returncode != 0:
        return f"g++ failed: {r.stderr[-2000:]}"
    return None


def load_native():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        if not os.path.exists(_LIB) or any(
                os.path.getmtime(s) > os.path.getmtime(_LIB)
                for s in _sources() if os.path.exists(s)):
            err = _build()
            if err:
                _lib_err = err
                return None
        lib = ctypes.CDLL(_LIB)
        u64, p8 = ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8)
        lib.pt_queue_create.restype = ctypes.c_void_p
        lib.pt_queue_create.argtypes = [u64]
        lib.pt_queue_push.restype = ctypes.c_int
        lib.pt_queue_push.argtypes = [ctypes.c_void_p, p8, u64]
        lib.pt_queue_pop.restype = ctypes.c_int
        lib.pt_queue_pop.argtypes = [ctypes.c_void_p, ctypes.POINTER(p8),
                                     ctypes.POINTER(u64)]
        lib.pt_queue_size.restype = u64
        lib.pt_queue_size.argtypes = [ctypes.c_void_p]
        lib.pt_queue_close.argtypes = [ctypes.c_void_p]
        lib.pt_queue_destroy.argtypes = [ctypes.c_void_p]
        lib.pt_buffer_free.argtypes = [p8]
        lib.pt_records_open.restype = ctypes.c_void_p
        lib.pt_records_open.argtypes = [ctypes.c_char_p]
        lib.pt_records_count.restype = u64
        lib.pt_records_count.argtypes = [ctypes.c_void_p]
        lib.pt_records_get.restype = ctypes.c_int
        lib.pt_records_get.argtypes = [ctypes.c_void_p, u64,
                                       ctypes.POINTER(p8), ctypes.POINTER(u64)]
        lib.pt_records_close.argtypes = [ctypes.c_void_p]
        lib.pt_reader_start.restype = ctypes.c_void_p
        lib.pt_reader_start.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                        u64, u64, u64, u64]
        lib.pt_reader_stop.argtypes = [ctypes.c_void_p]
        lib.pt_reader_done.restype = ctypes.c_int
        lib.pt_reader_done.argtypes = [ctypes.c_void_p]
        # rendezvous store daemon (native/src/store.cc)
        lib.pt_store_start.restype = ctypes.c_int
        lib.pt_store_start.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                       ctypes.POINTER(ctypes.c_int)]
        lib.pt_store_port.restype = ctypes.c_int
        lib.pt_store_port.argtypes = [ctypes.c_int]
        lib.pt_store_stop.argtypes = [ctypes.c_int]
        _lib = lib
    return _lib


def native_available() -> bool:
    return load_native() is not None


def native_error() -> Optional[str]:
    load_native()
    return _lib_err


# ------------------------------------------------------------- file format
def write_records(path: str, payloads: Iterable[bytes]):
    """Write a PTR1 record file (magic | u64 count | (u64 len | bytes)*)."""
    payloads = list(payloads)
    with open(path, "wb") as f:
        f.write(b"PTR1")
        f.write(struct.pack("<Q", len(payloads)))
        for p in payloads:
            f.write(struct.pack("<Q", len(p)))
            f.write(p)
    return path


def write_sample_records(path: str, samples: Iterable) -> str:
    """Pickle each sample into a record (numpy arrays stay raw-buffer)."""
    return write_records(path, (pickle.dumps(s, protocol=4) for s in samples))


# ------------------------------------------------------------- dataset view
class RecordFile:
    """mmap-indexed record file (zero-copy reads via the C++ core)."""

    def __init__(self, path: str):
        lib = load_native()
        if lib is None:
            raise RuntimeError(f"native pipeline unavailable: {_lib_err}")
        self._lib = lib
        self._h = lib.pt_records_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open record file {path}")
        self.path = path

    def __len__(self):
        return self._lib.pt_records_count(self._h)

    def get_bytes(self, i: int) -> bytes:
        data = ctypes.POINTER(ctypes.c_uint8)()
        size = ctypes.c_uint64()
        if self._lib.pt_records_get(self._h, i, ctypes.byref(data),
                                    ctypes.byref(size)) != 0:
            raise IndexError(i)
        return ctypes.string_at(data, size.value)

    def close(self):
        if self._h:
            self._lib.pt_records_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class RecordDataset:
    """Map-style Dataset over a PTR1 file (decodes pickle by default)."""

    def __init__(self, path: str, decode: Optional[Callable] = pickle.loads):
        self._file = RecordFile(path)
        self._decode = decode

    def __len__(self):
        return len(self._file)

    def __getitem__(self, i):
        b = self._file.get_bytes(i)
        return self._decode(b) if self._decode else b


class NativeRecordReader:
    """Threaded prefetching iterator: C++ reader threads fill a C++ blocking
    queue off-GIL; Python pops decoded samples.

    rank/world_size shard the record space (the reference's file-list split
    across trainers, data_feed.cc SetFileList), n_threads readers share the
    shard, `epochs` repeats it.
    """

    def __init__(self, path: str, queue_capacity: int = 64, n_threads: int = 2,
                 rank: int = 0, world_size: int = 1, epochs: int = 1,
                 decode: Optional[Callable] = pickle.loads):
        self._lib = load_native()
        if self._lib is None:
            raise RuntimeError(f"native pipeline unavailable: {_lib_err}")
        self._file = RecordFile(path)
        n = len(self._file)
        per = (n + world_size - 1) // world_size
        self._begin = min(rank * per, n)
        self._end = min(self._begin + per, n)
        self._total = (self._end - self._begin) * epochs
        self._decode = decode
        self._q = self._lib.pt_queue_create(queue_capacity)
        self._r = self._lib.pt_reader_start(self._file._h, self._q,
                                            self._begin, self._end,
                                            n_threads, epochs)
        self._popped = 0
        self._closed = False

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._popped >= self._total:
            self.close()
            raise StopIteration
        data = ctypes.POINTER(ctypes.c_uint8)()
        size = ctypes.c_uint64()
        rc = self._lib.pt_queue_pop(self._q, ctypes.byref(data),
                                    ctypes.byref(size))
        if rc != 0:
            self.close()
            raise StopIteration
        try:
            raw = ctypes.string_at(data, size.value)
        finally:
            self._lib.pt_buffer_free(data)
        self._popped += 1
        return self._decode(raw) if self._decode else raw

    def qsize(self) -> int:
        return self._lib.pt_queue_size(self._q)

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._lib.pt_reader_stop(self._r)
        self._lib.pt_queue_destroy(self._q)
        self._file.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class BlockingQueue:
    """Python handle on the C++ blocking queue (reference:
    framework/blocking_queue.h exposed via reader ops). Useful as a bounded
    hand-off between producer threads/processes and the host feed loop."""

    def __init__(self, capacity: int = 64):
        self._lib = load_native()
        if self._lib is None:
            raise RuntimeError(f"native pipeline unavailable: {_lib_err}")
        self._q = self._lib.pt_queue_create(capacity)
        self._destroyed = False

    def push(self, payload: bytes) -> bool:
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        return self._lib.pt_queue_push(self._q, buf, len(payload)) == 0

    def pop(self) -> Optional[bytes]:
        data = ctypes.POINTER(ctypes.c_uint8)()
        size = ctypes.c_uint64()
        if self._lib.pt_queue_pop(self._q, ctypes.byref(data),
                                  ctypes.byref(size)) != 0:
            return None
        try:
            return ctypes.string_at(data, size.value)
        finally:
            self._lib.pt_buffer_free(data)

    def size(self) -> int:
        return self._lib.pt_queue_size(self._q)

    def close(self):
        self._lib.pt_queue_close(self._q)

    def __del__(self):
        try:
            if not self._destroyed:
                self._destroyed = True
                self._lib.pt_queue_destroy(self._q)
        except Exception:
            pass
