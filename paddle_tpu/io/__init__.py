"""paddle.io analog — Dataset/Sampler/DataLoader.

Reference: python/paddle/io/ + fluid/dataloader/ (dataloader_iter.py:162
single-process, :370 multi-process with shared memory + C++ BlockingQueue).
TPU-native design: the loader produces numpy batches on host and ships them
with a background thread + double buffering (device_put overlap); there is no
forked-worker shared-memory machinery because the expensive path on TPU is
host→HBM transfer, which jax pipelines. A `places`-style API is kept for
signature parity.
"""
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    Subset, random_split,
)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    BatchSampler, DistributedBatchSampler,
)
from .dataloader import (DataLoader, DataLoaderTimeoutError,  # noqa: F401
                         SeededBatchSampler, default_collate_fn,
                         get_worker_info, WorkerInfo)

from .native import (  # noqa: E402,F401
    native_available, write_records, write_sample_records,
    RecordDataset, NativeRecordReader, BlockingQueue,
)
