"""paddle.utils analog — misc helper surface (reference:
python/paddle/utils/: deprecated decorator, try_import, unique_name,
flops, download stub)."""
from __future__ import annotations

import functools
import importlib
import itertools
import warnings


def deprecated(update_to="", since="", reason="", level=0):
    """reference: utils/deprecated.py decorator."""

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*a, **kw):
            msg = f"API {fn.__name__} is deprecated since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*a, **kw)
        return inner
    return wrap


def try_import(module_name, err_msg=None):
    """reference: utils/lazy_import.py try_import."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or
                          f"{module_name} is required but not installed "
                          "(installs are disabled in this environment)") from e


class _UniqueNameGenerator:
    def __init__(self):
        self._counters = {}

    def __call__(self, key=""):
        c = self._counters.setdefault(key, itertools.count())
        return f"{key}_{next(c)}"


generate = _UniqueNameGenerator()


class unique_name:
    """reference: fluid/unique_name.py."""
    _gen = _UniqueNameGenerator()

    @staticmethod
    def generate(key=""):
        return unique_name._gen(key)

    @staticmethod
    def guard(new_generator=None):
        import contextlib
        return contextlib.nullcontext()


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough FLOPs count via the hapi summary machinery (reference:
    paddle.flops → utils/op_summary)."""
    from ..hapi.summary import summary as _summary
    info = _summary(net, input_size)
    return info.get("total_params", 0) * 2 if isinstance(info, dict) else 0


def run_check():
    """reference: paddle.utils.run_check — sanity-check the install."""
    import jax
    import jax.numpy as jnp
    n = len(jax.devices())
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    assert float(y[0, 0]) == 128.0
    print(f"paddle_tpu is installed successfully! "
          f"{n} device(s) available: {jax.devices()[0].platform}")
