"""paddle.nn-equivalent namespace (reference: python/paddle/nn/__init__.py,
137 exported layer symbols)."""
from . import functional  # noqa: F401
from . import layout  # noqa: F401  (channels-last trunk annotation helpers)
from . import initializer  # noqa: F401
from .layer import (  # noqa: F401
    Layer, Sequential, LayerList, LayerDict, ParameterList, Identity, ParamAttr,
)
from .layers.common import (  # noqa: F401
    Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout, Flatten,
    Pad1D, Pad2D, Pad3D, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D,
    PixelShuffle, Unfold, Bilinear,
)
from .layers.conv import Conv1D, Conv2D, Conv3D, Conv2DTranspose  # noqa: F401
from .layers.norm import (  # noqa: F401
    LayerNorm, RMSNorm, BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
    SyncBatchNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm,
)
from .layers.activation import (  # noqa: F401
    ReLU, ReLU6, GELU, SiLU, Swish, ELU, SELU, CELU, LeakyReLU, PReLU, Sigmoid,
    Tanh, Softmax, LogSoftmax, Hardtanh, Hardsigmoid, Hardswish, Hardshrink,
    Softshrink, Tanhshrink, Mish, Softplus, Softsign, GLU, ThresholdedReLU, Maxout,
    Softmax2D,
)
from .layers.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, AvgPool1D, AvgPool2D, AdaptiveAvgPool1D,
    AdaptiveAvgPool2D, AdaptiveMaxPool2D,
)
from .layers.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    KLDivLoss, SmoothL1Loss, MarginRankingLoss, CTCLoss, CosineSimilarity,
    CosineEmbeddingLoss, TripletMarginLoss, HingeEmbeddingLoss,
    MultiMarginLoss, SoftMarginLoss, MultiLabelSoftMarginLoss, RNNTLoss,
    HSigmoidLoss,
)
from .layers.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layers.rnn import (  # noqa: F401
    SimpleRNN, LSTM, GRU, RNN, SimpleRNNCell, LSTMCell, GRUCell,
)
from .layers.decode import BeamSearchDecoder, dynamic_decode  # noqa: F401

from ..core.tensor import Parameter  # noqa: F401


class ClipGradByNorm:
    """Reference: paddle.nn.ClipGradByNorm (fluid/clip.py)."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm


class ClipGradByGlobalNorm:
    """Reference: paddle.nn.ClipGradByGlobalNorm (fluid/clip.py:449)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm


class ClipGradByValue:
    def __init__(self, max, min=None):  # noqa: A002
        self.max = max
        self.min = -max if min is None else min

from .layers.extras import (  # noqa: E402,F401
    MaxPool3D, AvgPool3D, AdaptiveMaxPool1D, AdaptiveMaxPool3D,
    AdaptiveAvgPool3D, Conv1DTranspose, Conv3DTranspose, SpectralNorm,
    RReLU, LogSigmoid, Silu, RNNCellBase, BiRNN, HuberLoss, SoftMarginLoss,
    MultiLabelSoftMarginLoss, PoissonNLLLoss, GaussianNLLLoss,
    PairwiseDistance, TripletMarginWithDistanceLoss, ZeroPad2D,
    PixelUnshuffle, ChannelShuffle, Fold, Unflatten, MaxUnPool1D,
    MaxUnPool2D, MaxUnPool3D,
)
