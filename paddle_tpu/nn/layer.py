"""nn.Layer — module base class.

Reference: python/paddle/fluid/dygraph/layers.py (Layer, __call__ at :1010,
state_dict machinery). Same user contract (parameters/buffers/sublayers,
state_dict round-trip, train/eval, hooks); TPU-native additions: every
parameter may carry a `pspec` (jax PartitionSpec) annotation used by
paddle_tpu.jit and paddle_tpu.distributed to shard the functional state under
pjit — this replaces the reference's per-layer process-group plumbing
(meta_parallel/*) with declarative sharding.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core.dtype import convert_dtype, get_default_dtype
from . import initializer as I


class HookRemoveHelper:
    def __init__(self, container, key):
        self._container, self._key = container, key

    def remove(self):
        self._container.pop(self._key, None)


class Layer:
    def __init__(self, name_scope: str = None, dtype=None):
        self._parameters: "collections.OrderedDict[str, Parameter]" = collections.OrderedDict()
        self._buffers: "collections.OrderedDict[str, Tensor]" = collections.OrderedDict()
        self._sub_layers: "collections.OrderedDict[str, Layer]" = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._hook_id = 0

    # ------------------------------------------------------------ attributes
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            layers.pop(name, None) if layers else None
            buffers.pop(name, None) if buffers else None
            object.__getattribute__(self, "__dict__").pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            params.pop(name, None) if params else None
            buffers.pop(name, None) if buffers else None
            object.__getattribute__(self, "__dict__").pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                elif isinstance(value, Tensor):
                    params[name] = value  # allow rebind
                else:
                    del params[name]
                    object.__setattr__(self, name, value)
                return
            if buffers is not None and name in buffers:
                if value is None:
                    del buffers[name]
                elif isinstance(value, Tensor):
                    buffers[name] = value
                else:
                    del buffers[name]
                    object.__setattr__(self, name, value)
                return
            if layers is not None and name in layers and value is None:
                del layers[name]
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ------------------------------------------------------------ builders
    def create_parameter(self, shape, attr=None, dtype=None, is_bias: bool = False,
                         default_initializer=None) -> Parameter:
        """Reference analog: Layer.create_parameter (layers.py) + ParamAttr."""
        dtype = convert_dtype(dtype) or self._dtype
        init = default_initializer
        if attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        data = init(shape, dtype)
        p = Parameter(data)
        if attr is not None and getattr(attr, "trainable", True) is False:
            p.trainable = False
        if attr is not None and getattr(attr, "name", None):
            p.name = attr.name
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ------------------------------------------------------------ traversal
    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(prefix=sub_prefix, include_self=True)

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None:
                    continue
                yield (f"{name}.{bname}" if name else bname), b
            if not include_sublayers:
                break

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------ state dict
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True
                   ) -> Dict[str, Tensor]:
        dest = destination if destination is not None else collections.OrderedDict()
        for name, layer in self.named_sublayers(prefix=structured_name_prefix.rstrip("."),
                                                include_self=True):
            for pname, p in layer._parameters.items():
                if p is not None:
                    dest[f"{name}.{pname}" if name else pname] = p
            for bname, b in layer._buffers.items():
                if b is not None and bname not in layer._non_persistable_buffer_names:
                    dest[f"{name}.{bname}" if name else bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        """Reference: Layer.set_state_dict (layers.py) — copies values into
        existing parameters (shape-checked), returns (missing, unexpected)."""
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            arr = v._data if isinstance(v, Tensor) else jnp.asarray(v)
            if tuple(arr.shape) != tuple(tgt._data.shape):
                raise ValueError(f"shape mismatch for {k}: {arr.shape} vs {tgt._data.shape}")
            tgt._data = arr.astype(tgt._data.dtype)
            tgt._node = None
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------ modes
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            for p in self.parameters():
                if p.is_floating_point():
                    p._data = p._data.astype(dt)
            for b in self.buffers():
                if b.is_floating_point():
                    b._data = b._data.astype(dt)
            for _, l in self.named_sublayers(include_self=True):
                l._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ------------------------------------------------------------ hooks/call
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            body = repr(layer).replace("\n", "\n  ")
            lines.append(f"  ({name}): {body}")
        main = f"{type(self).__name__}({extra}" + ("" if not lines else "\n" + "\n".join(lines) + "\n")
        return main + ")"

    def full_name(self):
        return self._name_scope

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


class Sequential(Layer):
    """Reference: paddle.nn.Sequential (fluid/dygraph/container.py)."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, l in layers[0].items():
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, tuple):
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    """Reference: paddle.nn.LayerList (fluid/dygraph/container.py)."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self


class Identity(Layer):
    def forward(self, x):
        return x


class ParamAttr:
    """Reference: paddle.ParamAttr (fluid/param_attr.py) — bag of param config."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


class LayerDict(Layer):
    """Reference: paddle.nn.LayerDict — dict-style sublayer container."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        layer = self._sub_layers[key]
        del self._sub_layers[key]
        return layer

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        items = sublayers.items() if hasattr(sublayers, "items") else sublayers
        for key, layer in items:
            self.add_sublayer(key, layer)
