"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..layer import Layer
from .. import functional as F
from .. import initializer as I
from ...core.tensor import Tensor


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """Beyond-reference: RMSNorm for modern LLM blocks (fp32 accumulation)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._epsilon = epsilon
        self.weight = self.create_parameter(shape=list(normalized_shape), attr=weight_attr,
                                            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        from .. import layout as _layout
        df = self._data_format
        if _layout.is_nhwc(x):
            if df == "NCHW":
                out = F.batch_norm(x, self._mean, self._variance, self.weight,
                                   self.bias, training=self.training,
                                   momentum=self._momentum,
                                   epsilon=self._epsilon, data_format="NHWC",
                                   use_global_stats=self._use_global_stats)
                return _layout.tag_nhwc(out)
            # declared NHWC: data already is — drop only the annotation
            x = _layout.untag(x) if df == "NHWC" else _layout.to_nchw(x)
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=df,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm (fluid) signature compat."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 use_global_stats=None, **kw):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            return F.relu(out)
        return out


class SyncBatchNorm(_BatchNormBase):
    """Reference: nn/layer/norm.py SyncBatchNorm (sync_batch_norm op). Under
    pjit/shard_map the batch axis is a mesh axis and XLA's batch-norm stats
    are computed over the global batch automatically in the jit path; the
    eager path here is single-host semantics.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon)
            out.weight, out.bias = layer.weight, layer.bias
            out._mean, out._variance = layer._mean, layer._variance
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups, self._epsilon = num_groups, epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias,
                            self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.scale = None if weight_attr is False else self.create_parameter(
            shape=[num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias, eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k,
                                     self.data_format)
