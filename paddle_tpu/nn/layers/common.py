"""Common layers: Linear, Embedding, Dropout, Flatten, Pad, Upsample.

Reference: python/paddle/nn/layer/common.py.
"""
from __future__ import annotations

import math

from ..layer import Layer
from .. import functional as F
from .. import initializer as I
from ...core.tensor import Tensor


class Linear(Layer):
    """y = xW + b with W: [in_features, out_features]
    (reference: nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self._in_features, self._out_features = in_features, out_features
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=None if (weight_attr and getattr(weight_attr, "initializer", None))
            else I.XavierUniform())
        if bias_attr is False:
            self.bias = None
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter(shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Embedding(Layer):
    """Lookup table (reference: nn/layer/common.py Embedding). `sparse` is
    accepted for API parity but is a no-op: on TPU the gather/scatter-add pair
    is already the efficient path, there are no sparse gradients."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (None if padding_idx is None
                             else padding_idx if padding_idx >= 0
                             else num_embeddings + padding_idx)
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0) if not (weight_attr and getattr(weight_attr, "initializer", None)) else None)
        if self._padding_idx is not None:
            import jax.numpy as jnp
            self.weight._data = self.weight._data.at[self._padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ...core.ops import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value, data_format=self.data_format)


class Pad2D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False,
                 align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners, self.align_mode = mode, align_corners, align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.kernel_sizes, self.strides = kernel_sizes, strides
        self.paddings, self.dilations = paddings, dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings, self.dilations)


class Bilinear(Layer):
    """Reference: nn/layer/common.py Bilinear — out[i] = x1 W_i x2 + b."""

    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[1, out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        from ...core.ops import einsum
        out = einsum("bi,oij,bj->bo", x1, self.weight, x2)
        if self.bias is not None:
            out = out + self.bias
        return out
