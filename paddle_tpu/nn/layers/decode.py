"""Beam-search decoding (reference: python/paddle/nn/layer/rnn.py
BeamSearchDecoder + python/paddle/nn/decode.py dynamic_decode).

TPU note: decoding is a python-driven loop over steps (the reference's
dynamic_decode while-loop); each step's cell call + beam bookkeeping is
jnp/XLA work, so under @to_static the whole rollout traces into one program
with a fixed max_step_num, the compiler-friendly form of the reference's
dynamic while op.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ...core import ops
from ..layer import Layer

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class BeamSearchDecoder:
    """reference: rnn.py BeamSearchDecoder (cell + embedding + projection)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- decoder protocol (initialize / step / finalize) ----------------
    def initialize(self, initial_cell_states):
        """Tile encoder states across beams; beam 0 live, others muted."""
        k = self.beam_size

        def tile(t):
            return apply_op(
                "beam_tile",
                lambda a: jnp.repeat(a, k, axis=0), [t])
        states = _map_structure(tile, initial_cell_states)
        batch = _first_leaf(states).shape[0] // k
        ids = ops.full([batch * k], self.start_token, "int64")
        # log-prob 0 for beam 0, -inf for the rest: first expansion seeds
        # distinct hypotheses instead of k copies
        lp0 = np.full((batch, k), -1e9, np.float32)
        lp0[:, 0] = 0.0
        log_probs = Tensor(jnp.asarray(lp0.reshape(-1)))
        finished = ops.zeros([batch * k], dtype="bool")
        return ids, states, log_probs, finished

    def step(self, inputs, states):
        x = self.embedding_fn(inputs) if self.embedding_fn else inputs
        cell_out, new_states = self.cell(x, states)
        logits = self.output_fn(cell_out) if self.output_fn else cell_out
        return logits, new_states


def _map_structure(fn, obj):
    if isinstance(obj, (list, tuple)):
        return type(obj)(_map_structure(fn, o) for o in obj)
    return fn(obj)


def _first_leaf(obj):
    while isinstance(obj, (list, tuple)):
        obj = obj[0]
    return obj


def _gather_beams(obj, beam_idx, batch, k):
    """Reindex [batch*k, ...] structures by per-batch beam choices."""
    def g(t):
        def fn(a, bi):
            a2 = a.reshape((batch, k) + a.shape[1:])
            out = jnp.take_along_axis(
                a2, bi.reshape(batch, k).astype(jnp.int32).reshape(
                    (batch, k) + (1,) * (a2.ndim - 2)), axis=1)
            return out.reshape((batch * k,) + a.shape[1:])
        return apply_op("beam_gather", fn, [t, beam_idx])
    return _map_structure(g, obj)


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """reference: decode.py dynamic_decode — drive a decoder until all beams
    finish or max_step_num. Returns (ids [batch, beam, T], final_log_probs)."""
    assert max_step_num is not None, "max_step_num is required"
    k = decoder.beam_size
    end = decoder.end_token
    ids, states, log_probs, finished = decoder.initialize(inits)
    batch = ids.shape[0] // k
    step_ids = []
    lengths = ops.zeros([batch * k], dtype="int64")

    for _ in range(int(max_step_num)):
        logits, new_states = decoder.step(ids, states)

        def expand(lg, lp, fin):
            v = lg.shape[-1]
            logp = jnp.log(jnp.maximum(1e-30, jnp.exp(
                lg - jnp.max(lg, -1, keepdims=True)) /
                jnp.sum(jnp.exp(lg - jnp.max(lg, -1, keepdims=True)),
                        -1, keepdims=True)))
            # finished beams only extend with end_token at no cost
            mask = jnp.full((v,), -1e9).at[end].set(0.0)
            logp = jnp.where(fin[:, None], mask[None, :], logp)
            total = lp[:, None] + logp                      # [batch*k, v]
            t2 = total.reshape(batch, k * v)
            top_lp, top_idx = jax.lax.top_k(t2, k)           # one O(kV) pass
            beam_idx = top_idx // v                          # [batch, k]
            tok = (top_idx % v).astype(jnp.int64)
            return (tok.reshape(-1), top_lp.reshape(-1),
                    beam_idx.reshape(-1))

        tok, log_probs, beam_idx = apply_op(
            "beam_expand", expand, [logits, log_probs, finished],
            n_outputs=3)
        states = _gather_beams(new_states, beam_idx, batch, k)
        finished = _gather_beams(finished, beam_idx, batch, k)
        lengths = _gather_beams(lengths, beam_idx, batch, k)
        prev_fin = finished

        def update(fin, ln, tk):
            now_end = tk.reshape(-1) == end
            new_fin = jnp.logical_or(fin, now_end)
            new_len = jnp.where(fin, ln, ln + 1)
            return new_fin, new_len
        finished, lengths = apply_op("beam_update", update,
                                     [finished, lengths, tok], n_outputs=2)
        step_ids = [_gather_beams(s, beam_idx, batch, k) for s in step_ids]
        step_ids.append(tok)
        ids = tok
        if bool(np.all(np.asarray(finished._data))):
            break

    out = ops.stack(step_ids, axis=-1)                      # [batch*k, T]
    out = ops.reshape(out, [batch, k, -1])
    if output_time_major:
        out = ops.transpose(out, [2, 0, 1])
    lp = ops.reshape(log_probs, [batch, k])
    if return_length:
        return out, lp, ops.reshape(lengths, [batch, k])
    return out, lp
