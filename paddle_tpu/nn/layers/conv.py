"""Convolution layers (reference: python/paddle/nn/layer/conv.py).

Weight layout [out_c, in_c // groups, *kernel] matching the reference so
state_dicts transfer; lowering is one lax.conv_general_dilated (MXU path).
"""
from __future__ import annotations

import numpy as np

from ..layer import Layer
from .. import functional as F
from .. import initializer as I
from .. import layout as _layout


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        if in_channels % groups or out_channels % groups:
            raise ValueError("channels must be divisible by groups")
        k = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size,) * n
        self._in_channels, self._out_channels = in_channels, out_channels
        self._kernel_size = tuple(k)
        self._stride, self._padding, self._dilation = stride, padding, dilation
        self._groups, self._data_format = groups, data_format
        self._padding_mode = padding_mode
        fan_in = in_channels // groups * int(np.prod(k))
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, *k], attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound)
            if not (weight_attr and getattr(weight_attr, "initializer", None)) else None)
        if bias_attr is False:
            self.bias = None
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound)
                if not (bias_attr and getattr(bias_attr, "initializer", None)) else None)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        # channels-last trunk propagation: an input tagged NHWC (see
        # nn.layout) computes directly in that layout and keeps the tag —
        # no transposes inside the trunk. A config that cannot honor the
        # tag exits the layout region instead of misreading the data.
        if _layout.is_nhwc(x):
            if self._data_format == "NCHW":
                out = F.conv2d(x, self.weight, self.bias, self._stride,
                               self._padding, self._dilation, self._groups,
                               "NHWC")
                return _layout.tag_nhwc(out)
            # declared NHWC: data already is — drop only the annotation
            x = _layout.untag(x) if self._data_format == "NHWC" \
                else _layout.to_nchw(x)
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size,) * 2
        self._stride, self._padding, self._output_padding = stride, padding, output_padding
        self._dilation, self._groups, self._data_format = dilation, groups, data_format
        fan_in = in_channels * int(np.prod(k)) // groups
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            shape=[in_channels, out_channels // groups, *k], attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound)
            if not (weight_attr and getattr(weight_attr, "initializer", None)) else None)
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride, self._padding,
                                  self._output_padding, self._groups, self._dilation,
                                  self._data_format, output_size)
