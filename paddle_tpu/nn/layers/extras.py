"""Remaining paddle.nn layer symbols (reference: python/paddle/nn/__init__.py
exports 137 layer classes; this module supplies the tail not covered by the
core layer files — 3-D pooling, transposed 1/3-D convs, spectral norm,
shuffle/fold utilities, unpooling, and the remaining loss formulas).

All are thin compositions over jnp/lax (one XLA lowering each); shapes
follow paddle conventions (NCHW-family)."""
from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, apply_op
from ...core import ops
from ...core import random as _random
from ..layer import Layer
from .. import functional as F
from .conv import Conv2DTranspose


def _arr(x):
    return x._data if isinstance(x, Tensor) else x


def _pair(v, n):
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v,) * n


# ------------------------------------------------------------ 3-D pooling
def _pool_nd(x, ksize, strides, padding, n, reducer, init, avg=False):
    k = (1, 1) + _pair(ksize, n)
    s = (1, 1) + _pair(strides, n)
    pads = [(0, 0), (0, 0)] + [(p, p) for p in _pair(padding, n)]

    def fn(a):
        out = lax.reduce_window(a, init, reducer, k, s,
                                [(lo, hi) for lo, hi in pads])
        if avg:
            ones = jnp.ones_like(a)
            cnt = lax.reduce_window(ones, 0.0, lax.add, k, s,
                                    [(lo, hi) for lo, hi in pads])
            out = out / cnt
        return out
    return apply_op("pool%dd" % n, fn, [x])


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.k, self.s = kernel_size, stride or kernel_size
        self.p = padding

    def forward(self, x):
        return _pool_nd(x, self.k, self.s, self.p, 3, lax.max, -jnp.inf)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.k, self.s = kernel_size, stride or kernel_size
        self.p = padding

    def forward(self, x):
        return _pool_nd(x, self.k, self.s, self.p, 3, lax.add, 0.0, avg=True)


def _adaptive_pool(x, out_sizes, nd, mode):
    """Adaptive pooling via integral bins (paddle adaptive semantics)."""
    shape = tuple(_arr(x).shape)
    spatial = shape[2:2 + nd]
    outs = _pair(out_sizes, nd)

    def fn(a):
        y = a
        for d, (in_s, out_s) in enumerate(zip(spatial, outs)):
            axis = 2 + d
            starts = (np.arange(out_s) * in_s) // out_s
            ends = -(-((np.arange(out_s) + 1) * in_s) // out_s)
            segs = []
            for st, en in zip(starts, ends):
                sl = [slice(None)] * y.ndim
                sl[axis] = slice(int(st), int(en))
                seg = y[tuple(sl)]
                seg = (jnp.max(seg, axis=axis, keepdims=True) if mode == "max"
                       else jnp.mean(seg, axis=axis, keepdims=True))
                segs.append(seg)
            y = jnp.concatenate(segs, axis=axis)
        return y
    return apply_op(f"adaptive_{mode}_pool{nd}d", fn, [x])


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, **kw):
        super().__init__()
        self.out = output_size

    def forward(self, x):
        return _adaptive_pool(x, self.out, 1, "max")


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, **kw):
        super().__init__()
        self.out = output_size

    def forward(self, x):
        return _adaptive_pool(x, self.out, 3, "max")


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, **kw):
        super().__init__()
        self.out = output_size

    def forward(self, x):
        return _adaptive_pool(x, self.out, 3, "avg")


# ----------------------------------------------------- transposed convs 1/3D
class Conv1DTranspose(Layer):
    """1-D transposed conv via the 2-D kernel on a dummy height dim."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 bias_attr=None, data_format="NCL", **kw):
        super().__init__()
        self._c2 = Conv2DTranspose(
            in_channels, out_channels, (1, kernel_size), stride=(1, stride),
            padding=(0, padding), output_padding=(0, output_padding),
            groups=groups, dilation=(1, dilation), bias_attr=bias_attr)

    @property
    def weight(self):
        return self._c2.weight

    def forward(self, x):
        y = ops.unsqueeze(x, 2)          # NCL -> NC1L
        y = self._c2(y)
        return ops.squeeze(y, 2)


class Conv3DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 bias_attr=None, data_format="NCDHW", **kw):
        super().__init__()
        from ..initializer import XavierUniform, Constant
        k = _pair(kernel_size, 3)
        self._stride = _pair(stride, 3)
        self._pad = _pair(padding, 3)
        self._out_pad = _pair(output_padding, 3)
        self._dil = _pair(dilation, 3)
        self._groups = groups
        init = XavierUniform()
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, *k],
            default_initializer=init)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], is_bias=True)

    def forward(self, x):
        stride, pad, dil, out_pad = (self._stride, self._pad, self._dil,
                                     self._out_pad)
        groups = self._groups

        def fn(a, w, *b):
            kd, kh, kw = w.shape[2:]
            padding_cfg = [
                (dil[i] * (k - 1) - pad[i], dil[i] * (k - 1) - pad[i] + out_pad[i])
                for i, k in enumerate((kd, kh, kw))]
            out = lax.conv_transpose(
                a, w, strides=stride, padding=padding_cfg, rhs_dilation=dil,
                dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
                transpose_kernel=True)
            if b:
                out = out + b[0].reshape(1, -1, 1, 1, 1)
            return out
        args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
        return apply_op("conv3d_transpose", fn, args)


# ----------------------------------------------------------- spectral norm
class SpectralNorm(Layer):
    """Power-iteration spectral normalization of a weight (reference:
    nn/layer/norm.py SpectralNorm — normalizes the layer's weight tensor;
    used through paddle.nn.utils.spectral_norm in practice)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, **kw):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = weight_shape[dim]
        w = int(np.prod([s for i, s in enumerate(weight_shape) if i != dim]))
        self.weight_u = self.create_parameter([h])
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter([w])
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        dim, iters, eps = self.dim, self.power_iters, self.eps

        def fn(w, u, v):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma
        return apply_op("spectral_norm", fn,
                        [weight, self.weight_u, self.weight_v])


# ------------------------------------------------------------- activations
class RReLU(Layer):
    """Randomized leaky ReLU (reference: nn/layer/activation.py RReLU)."""

    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, **kw):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        lo, hi = self.lower, self.upper
        if self.training:
            key = _random.op_key()

            def fn(a, k):
                slope = jax.random.uniform(k, a.shape, minval=lo, maxval=hi)
                return jnp.where(a >= 0, a, a * slope).astype(a.dtype)
            return apply_op("rrelu", fn, [x, key])
        mid = (lo + hi) / 2.0
        return apply_op("rrelu_eval",
                        lambda a: jnp.where(a >= 0, a, a * mid), [x])


class LogSigmoid(Layer):
    def forward(self, x):
        return apply_op("log_sigmoid", jax.nn.log_sigmoid, [x])


class Silu(Layer):
    def forward(self, x):
        return F.silu(x)


# ------------------------------------------------------------------- RNN
from .rnn import RNN as _RNN  # noqa: E402


class RNNCellBase(Layer):
    """Base for user-defined recurrent cells (reference: nn/layer/rnn.py
    RNNCellBase) — subclass with forward(inputs, states) -> (out, states)."""

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        hidden = shape or [getattr(self, "hidden_size", 1)]
        return ops.full([b] + list(hidden), init_value, dtype=dtype)


class BiRNN(Layer):
    """Bidirectional wrapper over two cells (reference: nn/layer/rnn.py
    BiRNN): concatenates forward and reversed-backward features."""

    def __init__(self, cell_fw, cell_bw, time_major=False, **kw):
        super().__init__()
        self.rnn_fw = _RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = _RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        fw, sf = self.rnn_fw(inputs, None if initial_states is None
                             else initial_states[0])
        bw, sb = self.rnn_bw(inputs, None if initial_states is None
                             else initial_states[1])
        return ops.concat([fw, bw], axis=-1), (sf, sb)


# ------------------------------------------------------------------ losses
def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0, **kw):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):  # noqa: A002
        d = self.delta

        def fn(x, y):
            r = jnp.abs(x - y)
            return jnp.where(r <= d, 0.5 * r * r, d * (r - 0.5 * d))
        return _reduce_loss(apply_op("huber", fn, [input, label]),
                            self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", **kw):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        # softplus form: stable for large |x| (log1p(exp(z)) overflows f32)
        out = apply_op("soft_margin",
                       lambda x, y: jax.nn.softplus(-y * x), [input, label])
        return _reduce_loss(out, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", **kw):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        def fn(x, y):
            return -(y * jax.nn.log_sigmoid(x)
                     + (1 - y) * jax.nn.log_sigmoid(-x)).mean(axis=-1)
        return _reduce_loss(apply_op("ml_soft_margin", fn, [input, label]),
                            self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", **kw):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.eps, self.reduction = epsilon, reduction

    def forward(self, input, label):  # noqa: A002
        log_input, full, eps = self.log_input, self.full, self.eps

        def fn(x, y):
            if log_input:
                loss = jnp.exp(x) - y * x
            else:
                loss = x - y * jnp.log(x + eps)
            if full:
                stirling = y * jnp.log(jnp.maximum(y, 1.0)) - y + \
                    0.5 * jnp.log(2 * math.pi * jnp.maximum(y, 1.0))
                loss = loss + jnp.where(y > 1, stirling, 0.0)
            return loss
        return _reduce_loss(apply_op("poisson_nll", fn, [input, label]),
                            self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", **kw):
        super().__init__()
        self.full, self.eps, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):  # noqa: A002
        full, eps = self.full, self.eps

        def fn(x, y, var):
            var = jnp.maximum(var, eps)
            loss = 0.5 * (jnp.log(var) + (x - y) ** 2 / var)
            if full:
                loss = loss + 0.5 * math.log(2 * math.pi)
            return loss
        return _reduce_loss(apply_op("gaussian_nll", fn,
                                     [input, label, variance]), self.reduction)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, **kw):
        super().__init__()
        self.p, self.eps, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        p, eps, keep = self.p, self.eps, self.keepdim

        def fn(a, b):
            d = jnp.abs(a - b) + eps
            return jnp.sum(d ** p, axis=-1, keepdims=keep) ** (1.0 / p)
        return apply_op("pairwise_distance", fn, [x, y])


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", **kw):
        super().__init__()
        self.dist = distance_function or (
            lambda a, b: PairwiseDistance()(a, b))
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):  # noqa: A002
        d_pos = self.dist(input, positive)
        d_neg = self.dist(input, negative)
        if self.swap:
            d_pn = self.dist(positive, negative)
            d_neg = ops.minimum(d_neg, d_pn)
        loss = ops.clip(d_pos - d_neg + self.margin, min=0.0)
        return _reduce_loss(loss, self.reduction)


# -------------------------------------------------------------- reshuffles
class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", **kw):
        super().__init__()
        self.padding = _pair(padding, 4) if isinstance(padding, (list, tuple)) \
            else (padding,) * 4

    def forward(self, x):
        l, r, t, b = self.padding
        return apply_op("zeropad2d",
                        lambda a: jnp.pad(a, [(0, 0), (0, 0), (t, b), (l, r)]),
                        [x])


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", **kw):
        super().__init__()
        self.r = downscale_factor

    def forward(self, x):
        r = self.r

        def fn(a):
            B, C, H, W = a.shape
            a = a.reshape(B, C, H // r, r, W // r, r)
            return a.transpose(0, 1, 3, 5, 2, 4).reshape(
                B, C * r * r, H // r, W // r)
        return apply_op("pixel_unshuffle", fn, [x])


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", **kw):
        super().__init__()
        self.groups = groups

    def forward(self, x):
        g = self.groups

        def fn(a):
            B, C, H, W = a.shape
            return a.reshape(B, g, C // g, H, W).transpose(0, 2, 1, 3, 4) \
                    .reshape(B, C, H, W)
        return apply_op("channel_shuffle", fn, [x])


class Fold(Layer):
    """col2im (reference: nn/layer/common.py Fold): inverse of Unfold."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, **kw):
        super().__init__()
        self.out_hw = _pair(output_sizes, 2)
        self.k = _pair(kernel_sizes, 2)
        self.s = _pair(strides, 2)
        self.p = _pair(paddings, 2)
        self.d = _pair(dilations, 2)

    def forward(self, x):
        OH, OW = self.out_hw
        kh, kw = self.k
        sh, sw = self.s
        ph, pw = self.p
        dh, dw = self.d

        def fn(a):
            B, CKK, L = a.shape
            C = CKK // (kh * kw)
            lh = (OH + 2 * ph - dh * (kh - 1) - 1) // sh + 1
            lw = (OW + 2 * pw - dw * (kw - 1) - 1) // sw + 1
            cols = a.reshape(B, C, kh, kw, lh, lw)
            out = jnp.zeros((B, C, OH + 2 * ph, OW + 2 * pw), a.dtype)
            for i in range(kh):
                for j in range(kw):
                    hi = i * dh
                    wj = j * dw
                    out = out.at[:, :, hi:hi + lh * sh:sh,
                                 wj:wj + lw * sw:sw].add(cols[:, :, i, j])
            return out[:, :, ph:ph + OH, pw:pw + OW]
        return apply_op("fold", fn, [x])


class Unflatten(Layer):
    def __init__(self, axis, shape, **kw):
        super().__init__()
        self.axis, self.shape = axis, list(shape)

    def forward(self, x):
        cur = list(x.shape)
        new = cur[:self.axis] + self.shape + cur[self.axis + 1:]
        return ops.reshape(x, new)


# ------------------------------------------------------------- unpooling
def _max_unpool_nd(x, indices, ksize, stride, padding, output_size, nd):
    def fn(a, idx):
        B, C = a.shape[:2]
        spatial_out = output_size
        flat_out = int(np.prod(spatial_out))
        a2 = a.reshape(B, C, -1)
        idx2 = idx.reshape(B, C, -1).astype(jnp.int32)
        out = jnp.zeros((B, C, flat_out), a.dtype)
        out = jax.vmap(jax.vmap(
            lambda o, v, i: o.at[i].set(v)))(out, a2, idx2)
        return out.reshape((B, C) + tuple(spatial_out))
    return apply_op("max_unpool%dd" % nd, fn, [x, indices])


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.k = kernel_size
        self.s = stride or kernel_size
        self.p = padding

    def forward(self, x, indices, output_size=None):
        L = x.shape[-1]
        out_l = output_size[-1] if output_size else (L - 1) * self.s + self.k \
            - 2 * self.p
        return _max_unpool_nd(x, indices, self.k, self.s, self.p, (out_l,), 1)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.k = _pair(kernel_size, 2)
        self.s = _pair(stride or kernel_size, 2)
        self.p = _pair(padding, 2)

    def forward(self, x, indices, output_size=None):
        H, W = x.shape[-2:]
        if output_size:
            oh, ow = output_size[-2:]
        else:
            oh = (H - 1) * self.s[0] + self.k[0] - 2 * self.p[0]
            ow = (W - 1) * self.s[1] + self.k[1] - 2 * self.p[1]
        return _max_unpool_nd(x, indices, self.k, self.s, self.p, (oh, ow), 2)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.k = _pair(kernel_size, 3)
        self.s = _pair(stride or kernel_size, 3)
        self.p = _pair(padding, 3)

    def forward(self, x, indices, output_size=None):
        D, H, W = x.shape[-3:]
        if output_size:
            od, oh, ow = output_size[-3:]
        else:
            od = (D - 1) * self.s[0] + self.k[0] - 2 * self.p[0]
            oh = (H - 1) * self.s[1] + self.k[1] - 2 * self.p[1]
            ow = (W - 1) * self.s[2] + self.k[2] - 2 * self.p[2]
        return _max_unpool_nd(x, indices, self.k, self.s, self.p,
                              (od, oh, ow), 3)
