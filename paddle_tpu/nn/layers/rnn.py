"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

The reference implements RNNs as per-step cells driven by an imperative loop
(or cudnn fused kernels). TPU-native design: the whole time loop is a single
`lax.scan` inside one tape op — XLA compiles it to one fused loop, and the
scan transposes cleanly under vjp for BPTT. Weight naming matches the
reference (weight_ih_l{k}, weight_hh_l{k}, ...) for state_dict parity.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..layer import Layer
from .. import initializer as I
from ...core.tensor import Tensor, apply_op
from ...core import ops


def _cell_params(layer, input_size, hidden_size, gates, suffix):
    bound = 1.0 / math.sqrt(hidden_size)
    w_ih = layer.create_parameter([gates * hidden_size, input_size],
                                  default_initializer=I.Uniform(-bound, bound))
    w_hh = layer.create_parameter([gates * hidden_size, hidden_size],
                                  default_initializer=I.Uniform(-bound, bound))
    b_ih = layer.create_parameter([gates * hidden_size], is_bias=True,
                                  default_initializer=I.Uniform(-bound, bound))
    b_hh = layer.create_parameter([gates * hidden_size], is_bias=True,
                                  default_initializer=I.Uniform(-bound, bound))
    layer.add_parameter(f"weight_ih_{suffix}", w_ih)
    layer.add_parameter(f"weight_hh_{suffix}", w_hh)
    layer.add_parameter(f"bias_ih_{suffix}", b_ih)
    layer.add_parameter(f"bias_hh_{suffix}", b_hh)
    return w_ih, w_hh, b_ih, b_hh


def _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh):
    z = x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh):
    gi = x_t @ w_ih.T + b_ih
    gh = h @ w_hh.T + b_hh
    ir, iz, in_ = jnp.split(gi, 3, axis=-1)
    hr, hz, hn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(in_ + r * hn)
    return (1 - z) * n + z * h


def _rnn_step(x_t, h, w_ih, w_hh, b_ih, b_hh, act):
    out = x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    return jnp.tanh(out) if act == "tanh" else jax.nn.relu(out)


class SimpleRNNCell(Layer):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        _cell_params(self, input_size, hidden_size, 1, "l0")

    def forward(self, inputs, states=None):
        if states is None:
            states = ops.zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)
        act = self.activation
        out = apply_op("rnn_cell",
                       lambda x, h, wi, wh, bi, bh: _rnn_step(x, h, wi, wh, bi, bh, act),
                       [inputs, states, self.weight_ih_l0, self.weight_hh_l0,
                        self.bias_ih_l0, self.bias_hh_l0])
        return out, out


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        _cell_params(self, input_size, hidden_size, 4, "l0")

    def forward(self, inputs, states=None):
        if states is None:
            z = ops.zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)
            states = (z, ops.zeros_like(z))
        h, c = states
        h_new, c_new = apply_op(
            "lstm_cell",
            lambda x, hh, cc, wi, wh, bi, bh: _lstm_step(x, hh, cc, wi, wh, bi, bh),
            [inputs, h, c, self.weight_ih_l0, self.weight_hh_l0,
             self.bias_ih_l0, self.bias_hh_l0], n_outputs=2)
        return h_new, (h_new, c_new)


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        _cell_params(self, input_size, hidden_size, 3, "l0")

    def forward(self, inputs, states=None):
        if states is None:
            states = ops.zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)
        out = apply_op("gru_cell",
                       lambda x, h, wi, wh, bi, bh: _gru_step(x, h, wi, wh, bi, bh),
                       [inputs, states, self.weight_ih_l0, self.weight_hh_l0,
                        self.bias_ih_l0, self.bias_hh_l0])
        return out, out


class _RNNBase(Layer):
    MODE_GATES = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}

    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh"):
        super().__init__()
        self.mode = mode
        self.input_size, self.hidden_size = input_size, hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirectional else 1
        gates = self.MODE_GATES[mode]
        for layer_i in range(num_layers):
            in_size = input_size if layer_i == 0 else hidden_size * self.num_directions
            _cell_params(self, in_size, hidden_size, gates, f"l{layer_i}")
            if self.bidirectional:
                _cell_params(self, in_size, hidden_size, gates, f"l{layer_i}_reverse")

    def _params_for(self, layer_i, reverse):
        sfx = f"l{layer_i}" + ("_reverse" if reverse else "")
        return (getattr(self, f"weight_ih_{sfx}"), getattr(self, f"weight_hh_{sfx}"),
                getattr(self, f"bias_ih_{sfx}"), getattr(self, f"bias_hh_{sfx}"))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # normalize to batch-major [B, T, C]
        x = inputs if not self.time_major else ops.transpose(inputs, [1, 0, 2])
        b = x.shape[0]
        mode = self.mode
        is_lstm = mode == "LSTM"
        n_states = self.num_layers * self.num_directions

        if initial_states is None:
            z = ops.zeros([n_states, b, self.hidden_size], dtype=x.dtype)
            initial_states = (z, ops.zeros_like(z)) if is_lstm else z

        h0 = initial_states[0] if is_lstm else initial_states
        c0 = initial_states[1] if is_lstm else None

        all_params = []
        for li in range(self.num_layers):
            for rev in (False, True) if self.bidirectional else (False,):
                all_params.extend(self._params_for(li, rev))

        num_layers, num_dirs = self.num_layers, self.num_directions
        hidden = self.hidden_size

        def fn(xx, hh0, *rest):
            if is_lstm:
                cc0 = rest[0]
                flat = rest[1:]
            else:
                cc0 = None
                flat = rest
            layer_in = jnp.swapaxes(xx, 0, 1)  # [T, B, C]
            h_finals, c_finals = [], []
            pi = 0
            for li in range(num_layers):
                dir_outs = []
                for d in range(num_dirs):
                    wi, wh, bi_, bh = flat[pi:pi + 4]
                    pi += 4
                    idx = li * num_dirs + d
                    h_init = hh0[idx]
                    seq = layer_in if d == 0 else jnp.flip(layer_in, axis=0)
                    if is_lstm:
                        c_init = cc0[idx]

                        def step(carry, x_t, wi=wi, wh=wh, bi_=bi_, bh=bh):
                            h, c = carry
                            h2, c2 = _lstm_step(x_t, h, c, wi, wh, bi_, bh)
                            return (h2, c2), h2
                        (h_f, c_f), outs = lax.scan(step, (h_init, c_init), seq)
                        c_finals.append(c_f)
                    elif mode == "GRU":
                        def step(h, x_t, wi=wi, wh=wh, bi_=bi_, bh=bh):
                            h2 = _gru_step(x_t, h, wi, wh, bi_, bh)
                            return h2, h2
                        h_f, outs = lax.scan(step, h_init, seq)
                    else:
                        act = "tanh" if mode == "RNN_TANH" else "relu"

                        def step(h, x_t, wi=wi, wh=wh, bi_=bi_, bh=bh, act=act):
                            h2 = _rnn_step(x_t, h, wi, wh, bi_, bh, act)
                            return h2, h2
                        h_f, outs = lax.scan(step, h_init, seq)
                    h_finals.append(h_f)
                    if d == 1:
                        outs = jnp.flip(outs, axis=0)
                    dir_outs.append(outs)
                layer_in = jnp.concatenate(dir_outs, axis=-1) if num_dirs == 2 else dir_outs[0]
            out = jnp.swapaxes(layer_in, 0, 1)  # [B, T, H*dirs]
            h_stack = jnp.stack(h_finals, axis=0)
            if is_lstm:
                return out, h_stack, jnp.stack(c_finals, axis=0)
            return out, h_stack

        args = [x, h0] + ([c0] if is_lstm else []) + all_params
        if is_lstm:
            out, h_n, c_n = apply_op(mode, fn, args, n_outputs=3)
            final = (h_n, c_n)
        else:
            out, h_n = apply_op(mode, fn, args, n_outputs=2)
            final = h_n
        if self.time_major:
            out = ops.transpose(out, [1, 0, 2])
        return out, final


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kw):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class RNN(Layer):
    """Generic cell driver (reference: nn/layer/rnn.py RNN) — python loop over
    time for arbitrary cells; prefer LSTM/GRU classes for compiled scans."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse, self.time_major = is_reverse, time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs if not self.time_major else ops.transpose(inputs, [1, 0, 2])
        steps = range(x.shape[1])
        if self.is_reverse:
            steps = reversed(list(steps))
        state = initial_states
        outs = []
        for tstep in steps:
            out, state = self.cell(x[:, tstep], state)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        out = ops.stack(outs, axis=1)
        if self.time_major:
            out = ops.transpose(out, [1, 0, 2])
        return out, state
