"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from ..layer import Layer
from .. import functional as F
from .. import initializer as I


def _act_layer(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, *a, name=None, **kw):
            super().__init__()
            merged = dict(defaults)
            keys = list(defaults.keys())
            for i, v in enumerate(a):
                merged[keys[i]] = v
            merged.update({k: v for k, v in kw.items() if k in merged})
            self._kw = merged

        def forward(self, x):
            return fn(x, **self._kw)
    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _act_layer("ReLU", lambda x: F.relu(x))
ReLU6 = _act_layer("ReLU6", lambda x: F.relu6(x))
GELU = _act_layer("GELU", F.gelu, approximate=False)
SiLU = _act_layer("SiLU", lambda x: F.silu(x))
Swish = _act_layer("Swish", lambda x: F.silu(x))
ELU = _act_layer("ELU", F.elu, alpha=1.0)
SELU = _act_layer("SELU", lambda x: F.selu(x))
CELU = _act_layer("CELU", F.celu, alpha=1.0)
LeakyReLU = _act_layer("LeakyReLU", F.leaky_relu, negative_slope=0.01)
Sigmoid = _act_layer("Sigmoid", lambda x: F.sigmoid(x))
Tanh = _act_layer("Tanh", lambda x: F.tanh(x))
Softmax = _act_layer("Softmax", F.softmax, axis=-1)
LogSoftmax = _act_layer("LogSoftmax", F.log_softmax, axis=-1)
Hardtanh = _act_layer("Hardtanh", F.hardtanh, min=-1.0, max=1.0)
Hardsigmoid = _act_layer("Hardsigmoid", lambda x: F.hardsigmoid(x))
Hardswish = _act_layer("Hardswish", lambda x: F.hardswish(x))
Hardshrink = _act_layer("Hardshrink", F.hardshrink, threshold=0.5)
Softshrink = _act_layer("Softshrink", F.softshrink, threshold=0.5)
Tanhshrink = _act_layer("Tanhshrink", lambda x: F.tanhshrink(x))
Mish = _act_layer("Mish", lambda x: F.mish(x))
Softplus = _act_layer("Softplus", F.softplus, beta=1.0, threshold=20.0)
Softsign = _act_layer("Softsign", lambda x: F.softsign(x))
GLU = _act_layer("GLU", F.glu, axis=-1)
ThresholdedReLU = _act_layer("ThresholdedReLU",
                             lambda x, threshold=1.0: x * (x > threshold).astype(x.dtype),
                             threshold=1.0)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class Softmax2D(Layer):
    """reference: nn/layer/activation.py Softmax2D — softmax over the
    channel axis of NCHW / CHW inputs."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        assert x.ndim in (3, 4), "Softmax2D expects CHW or NCHW"
        from .. import functional as F
        return F.softmax(x, axis=-3)
