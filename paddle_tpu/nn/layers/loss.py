"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from ..layer import Layer
from .. import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                 name=None):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = weight, ignore_index, reduction
        self.soft_label, self.axis = soft_label, axis
        self.use_softmax, self.label_smoothing = use_softmax, label_smoothing

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index, reduction=self.reduction,
                               soft_label=self.soft_label, axis=self.axis,
                               use_softmax=self.use_softmax,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = weight, ignore_index, reduction

    def forward(self, input, label):  # noqa: A002
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):  # noqa: A002
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight, self.reduction, self.pos_weight = weight, reduction, pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight,
                                                  self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.kl_div(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):  # noqa: A002
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):  # noqa: A002
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin, self.p, self.epsilon, self.swap = margin, p, epsilon, swap
        self.reduction = reduction

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_loss(input, positive, negative, self.margin, self.p,
                                     self.epsilon, self.swap, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):  # noqa: A002
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class MultiMarginLoss(Layer):
    """reference: nn/layer/loss.py MultiMarginLoss."""

    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin, self.weight = p, margin, weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_margin_loss(input, label, p=self.p, margin=self.margin,
                                   weight=self.weight,
                                   reduction=self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class RNNTLoss(Layer):
    """reference: nn/layer/loss.py RNNTLoss (warprnnt)."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank, self.fastemit_lambda = blank, fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):  # noqa: A002
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)


class HSigmoidLoss(Layer):
    """reference: nn/layer/loss.py HSigmoidLoss — owns the inner-node
    weight table [num_classes-1, feature_size]."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError("custom-tree hsigmoid not supported")
        self.num_classes = num_classes
        from .. import initializer as I
        import math as _m
        std = 1.0 / _m.sqrt(feature_size)
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size],
            default_initializer=I.Uniform(-std, std))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_classes - 1], is_bias=True,
                default_initializer=I.Uniform(-std, std))

    def forward(self, input, label, path_table=None, path_code=None):  # noqa: A002
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)
