"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from ..layer import Layer
from .. import functional as F
from .. import layout as _layout


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.return_mask, self.ceil_mode = return_mask, ceil_mode

    def forward(self, x):
        return F.max_pool1d(x, self.k, self.s, self.p, self.return_mask, self.ceil_mode)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.return_mask, self.ceil_mode, self.data_format = return_mask, ceil_mode, data_format

    def forward(self, x):
        if _layout.is_nhwc(x):
            if self.data_format == "NCHW" and not self.return_mask:
                out = F.max_pool2d(x, self.k, self.s, self.p, self.ceil_mode,
                                   False, "NHWC")
                return _layout.tag_nhwc(out)
            # declared NHWC: data already is — drop only the annotation
            x = _layout.untag(x) if self.data_format == "NHWC" \
                else _layout.to_nchw(x)
        return F.max_pool2d(x, self.k, self.s, self.p, self.ceil_mode,
                            self.return_mask, self.data_format)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.k, self.s, self.p, self.exclusive = kernel_size, stride, padding, exclusive

    def forward(self, x):
        return F.avg_pool1d(x, self.k, self.s, self.p, self.exclusive)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.ceil_mode, self.exclusive, self.divisor = ceil_mode, exclusive, divisor_override
        self.data_format = data_format

    def forward(self, x):
        if _layout.is_nhwc(x):
            if self.data_format == "NCHW":
                out = F.avg_pool2d(x, self.k, self.s, self.p, self.ceil_mode,
                                   self.exclusive, self.divisor, "NHWC")
                return _layout.tag_nhwc(out)
            x = _layout.untag(x) if self.data_format == "NHWC" \
                else _layout.to_nchw(x)
        return F.avg_pool2d(x, self.k, self.s, self.p, self.ceil_mode, self.exclusive,
                            self.divisor, self.data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size, self.data_format = output_size, data_format

    def forward(self, x):
        if _layout.is_nhwc(x):
            if self.data_format == "NCHW":
                out = F.adaptive_avg_pool2d(x, self.output_size, "NHWC")
                return _layout.tag_nhwc(out)
            x = _layout.untag(x) if self.data_format == "NHWC" \
                else _layout.to_nchw(x)
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size, self.return_mask = output_size, return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.return_mask)
