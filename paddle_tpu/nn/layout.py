"""Channels-last layout propagation for the vision conv trunk.

TPUs strongly prefer NHWC activations and HWIO kernels: the MXU consumes the
channel (contraction) dimension from the minor-most axis, so NCHW convs force
XLA to insert relayouts around every conv. Under `FLAGS_conv_channels_last`
the vision models run their conv trunk *internally* channels-last while the
public API stays NCHW:

- entry (`to_nhwc`) transposes once and tags the tensor with an internal
  `_layout = "NHWC"` annotation;
- layout-aware layers (Conv2D, BatchNorm2D, pools, the fused conv epilogues)
  see the tag, compute directly in NHWC, and propagate the tag;
- exit (`to_nchw`) transposes back exactly once at the trunk boundary.

The tag lives on the eager Tensor wrapper (core.tensor Tensor._layout), so it
propagates identically in eager mode and inside jit traces (TrainStep
re-executes the Python forward per trace). Ops that are not layout-aware
produce untagged tensors — the annotation never silently escapes the trunk:
a model must opt in by calling `to_nhwc` at a known boundary.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core import flags as _flags
from ..core.tensor import Tensor, apply_op

NHWC = "NHWC"


def channels_last_enabled() -> bool:
    """True when FLAGS_conv_channels_last is set."""
    return bool(_flags.conv_channels_last)


def is_nhwc(t) -> bool:
    """True when `t` carries the internal channels-last annotation."""
    return isinstance(t, Tensor) and getattr(t, "_layout", None) == NHWC


def tag_nhwc(t: Tensor) -> Tensor:
    t._layout = NHWC
    return t


def to_nhwc(x: Tensor) -> Tensor:
    """Trunk entry: NCHW -> physically-NHWC tensor tagged for propagation."""
    if is_nhwc(x):
        return x
    out = apply_op("layout_to_nhwc",
                   lambda a: jnp.transpose(a, (0, 2, 3, 1)), [x])
    return tag_nhwc(out)


def to_nchw(x: Tensor) -> Tensor:
    """Trunk exit: restore the API NCHW layout (no-op on untagged input)."""
    if not is_nhwc(x):
        return x
    out = apply_op("layout_to_nchw",
                   lambda a: jnp.transpose(a, (0, 3, 1, 2)), [x])
    out._layout = None
    return out


def untag(x: Tensor) -> Tensor:
    """Drop the annotation WITHOUT moving data — for handing a tagged
    tensor to a consumer whose declared data_format already is NHWC (the
    physical layout matches; only the bookkeeping must not leak). Returns a
    fresh wrapper sharing the array and autograd edge; the caller's tensor
    keeps its tag."""
    if not is_nhwc(x):
        return x
    out = Tensor(x._data, stop_gradient=x.stop_gradient)
    out._node = x._node
    out._out_idx = x._out_idx
    return out
