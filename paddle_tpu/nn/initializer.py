"""Weight initializers (reference: python/paddle/nn/initializer/*).

Each initializer is a callable (shape, dtype) -> jax array, drawing from the
global eager RNG stream. Fan computation mirrors the reference's
XavierInitializer/MSRAInitializer math (fluid/initializer.py).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import random as _random
from ..core.dtype import convert_dtype


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: [out_c, in_c, *spatial] receptive field product
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


_fast_init_depth = 0


def fast_init():
    """Context manager: random initializers return zeros (structural init).

    For memory planning / AOT compilation of very large models, where
    drawing billions of random values on a single host would dominate setup
    time and the VALUES are irrelevant (only shapes/shardings matter) —
    used by __graft_entry__'s 6.7B memory plan. Constant/Assign/Dirac
    initializers are unaffected.
    """
    import contextlib

    @contextlib.contextmanager
    def cm():
        global _fast_init_depth
        _fast_init_depth += 1
        try:
            yield
        finally:
            _fast_init_depth -= 1

    return cm()


def _fast_zeros(shape, dtype):
    if _fast_init_depth:
        return jnp.zeros(tuple(shape), convert_dtype(dtype))
    return None


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(shape, self.value, dtype=convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        z = _fast_zeros(shape, dtype)
        if z is not None:
            return z
        dt = convert_dtype(dtype)
        return self.mean + self.std * jax.random.normal(_random.split_key(), tuple(shape), dtype=dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        z = _fast_zeros(shape, dtype)
        if z is not None:
            return z
        dt = convert_dtype(dtype)
        x = jax.random.truncated_normal(_random.split_key(), -2.0, 2.0, tuple(shape), dtype=dt)
        return self.mean + self.std * x


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        z = _fast_zeros(shape, dtype)
        if z is not None:
            return z
        dt = convert_dtype(dtype)
        return jax.random.uniform(_random.split_key(), tuple(shape), dtype=dt,
                                  minval=self.low, maxval=self.high)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None):
        self.fan_in, self.fan_out = fan_in, fan_out

    def __call__(self, shape, dtype="float32"):
        z = _fast_zeros(shape, dtype)
        if z is not None:
            return z
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None):
        self.fan_in, self.fan_out = fan_in, fan_out

    def __call__(self, shape, dtype="float32"):
        z = _fast_zeros(shape, dtype)
        if z is not None:
            return z
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="leaky_relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype="float32"):
        z = _fast_zeros(shape, dtype)
        if z is not None:
            return z
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="leaky_relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype="float32"):
        z = _fast_zeros(shape, dtype)
        if z is not None:
            return z
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        return Normal(0.0, gain / math.sqrt(fi))(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        arr = np.asarray(self.value) if not hasattr(self.value, "_data") else np.asarray(self.value._data)
        assert tuple(arr.shape) == tuple(shape), f"Assign shape {arr.shape} != {shape}"
        return jnp.asarray(arr, dtype=convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        z = _fast_zeros(shape, dtype)
        if z is not None:
            return z
        dt = convert_dtype(dtype)
        rows, cols = shape[0], int(np.prod(shape[1:]))
        flat = jax.random.normal(_random.split_key(), (max(rows, cols), min(rows, cols)))
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        return (self.gain * q[:rows, :cols].reshape(shape)).astype(dt)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        out_c, in_c = shape[0], shape[1]
        arr = np.zeros(shape, dtype=np.float32)
        centers = [s // 2 for s in shape[2:]]
        per = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(per, in_c)):
                arr[(g * per + i, i, *centers)] = 1.0
        return jnp.asarray(arr, dtype=convert_dtype(dtype))


# paddle-style aliases
constant_init = Constant
normal_init = Normal
