"""nn.functional — neural-net functional ops.

Reference: python/paddle/nn/functional/* (common.py:1814 linear, conv.py,
pooling.py, loss.py, activation.py, norm.py). One lowering per op to
jax.lax/jnp: XLA fuses elementwise chains into matmul/conv epilogues on TPU,
which is why there is no separate "fused op" corpus here (the reference's
operators/fused/* exists because CUDA needs hand-fused kernels; on TPU the
compiler does it, and the few genuinely hard fusions — flash attention —
live in paddle_tpu.ops.pallas as Pallas kernels).
"""
from __future__ import annotations

import builtins
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply_op
from ..core.dtype import convert_dtype
from ..core import random as _random
from ..core.ops import (  # re-exported op-level functions  # noqa: F401
    relu, softmax, log_softmax, sigmoid, tanh,
)

__all__ = [
    "linear", "embedding", "one_hot",
    "conv1d", "conv2d", "conv3d", "conv2d_transpose", "fused_conv_bn_act",
    "max_pool1d", "max_pool2d", "avg_pool1d", "avg_pool2d",
    "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_max_pool2d",
    "relu", "relu6", "gelu", "silu", "swish", "elu", "selu", "celu",
    "leaky_relu", "prelu", "hardshrink", "softshrink", "tanhshrink",
    "hardtanh", "hardsigmoid", "hardswish", "mish", "softplus", "softsign",
    "sigmoid", "tanh", "softmax", "log_softmax", "gumbel_softmax", "glu",
    "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "layer_norm", "batch_norm", "group_norm", "instance_norm", "rms_norm",
    "local_response_norm", "normalize",
    "cross_entropy", "softmax_with_cross_entropy", "nll_loss", "mse_loss",
    "l1_loss", "smooth_l1_loss", "kl_div", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "margin_ranking_loss",
    "cosine_similarity", "cosine_embedding_loss", "ctc_loss", "hinge_embedding_loss",
    "square_error_cost", "log_loss", "sigmoid_focal_loss", "triplet_margin_loss",
    "pad", "interpolate", "upsample", "pixel_shuffle", "unfold",
    "scaled_dot_product_attention", "label_smooth", "temporal_shift",
    "sequence_mask", "grid_sample", "affine_grid",
]


def _arr(x):
    return x._data if isinstance(x, Tensor) else x


# ----------------------------------------------------------------- dense
def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W stored [in, out] (reference: functional/common.py:1814).

    Single MXU matmul; bias add fuses into the epilogue under XLA.
    """
    if bias is None:
        return apply_op("linear", lambda a, w: a @ w, [x, weight])
    return apply_op("linear", lambda a, w, b: a @ w + b, [x, weight, bias])


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Reference: functional/input.py embedding. Gather from the table; rows
    at padding_idx produce zero gradient (masked in fwd so vjp zeroes it)."""
    def fn(idx, w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply_op("embedding", fn, [x, weight])


def one_hot(x, num_classes, name=None):
    return apply_op("one_hot",
                    lambda i: jax.nn.one_hot(i, num_classes, dtype=jnp.float32),
                    [x])


# ----------------------------------------------------------------- convs
_CHANNEL_LAST_FORMATS = ("NHWC", "NLC", "NDHWC", "NHC")


def _conv_dn(ndim, channel_last=False):
    """Dimension numbers. Channel-last uses the TPU-preferred HWIO kernel
    layout (channel contraction minor-most for both operands — the layout
    the MXU wants; OIHW kernels force a relayout in front of every conv)."""
    if ndim == 1:
        return ("NHC", "HIO", "NHC") if channel_last else ("NCH", "OIH", "NCH")
    if ndim == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


# per-param cache of channels-last kernel transposes for the non-recording
# (inference/no-grad) eager path. Entries hold only a WEAKREF to the source
# array (jax arrays are immutable and weakref-able), so a dropped model's
# kernels — and their HWIO copies — become collectable as soon as the
# originals die; dead entries are purged opportunistically on insert. The id
# key is validated against the live referent, so id reuse cannot alias.
_W_CL_CACHE: "dict[int, tuple]" = {}   # id(w) -> (weakref(w), w_transposed)
_W_CL_CACHE_MAX = 512


def clear_channels_last_weight_cache():
    """Drop all cached HWIO kernel transposes (see _cl_weight_cached)."""
    _W_CL_CACHE.clear()
    _FOLD_CACHE.clear()


def _static_recording_active():
    """True while static-mode Program recording is capturing ops: any
    hoisted concrete array would be baked into the Program as a CONSTANT
    instead of a parameter reference, silently pinning stale weights."""
    from ..core import tensor as _ct
    if _ct._static_record is None:
        return False
    from ..static.program import _recording_active
    return _recording_active()


def _cl_weight_cached(weight, perm):
    """Return the pre-transposed HWIO kernel for `weight` when it is safe to
    take the transpose OUT of the autograd graph (weight not differentiated
    this call, no static recording), else None (caller transposes inside the
    op fn, which under jit happens once per trace)."""
    import weakref
    from ..core import autograd as _autograd
    if not isinstance(weight, Tensor):
        return None
    if _autograd.is_grad_enabled() and (not weight.stop_gradient
                                        or weight._node is not None):
        return None  # gradient must flow through the transpose
    w = weight._data
    if isinstance(w, jax.core.Tracer):
        return None
    if _static_recording_active():
        return None
    key = id(w)
    hit = _W_CL_CACHE.get(key)
    if hit is not None and hit[0]() is w:
        return hit[1]
    wt = jnp.transpose(w, perm)
    for k in [k for k, (r, _) in _W_CL_CACHE.items() if r() is None]:
        del _W_CL_CACHE[k]
    if len(_W_CL_CACHE) >= _W_CL_CACHE_MAX:
        _W_CL_CACHE.pop(next(iter(_W_CL_CACHE)))
    try:
        _W_CL_CACHE[key] = (weakref.ref(w), wt)
    except TypeError:
        return wt  # non-weakrefable array type: serve uncached
    return wt


def _norm_tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _conv_padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and builtins.all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in builtins.range(n)]
    return [tuple(p) for p in padding]


def _convnd(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    from ..core import flags as _flags
    channel_last = data_format in _CHANNEL_LAST_FORMATS
    # internal channels-last compute whenever the data already is, or the
    # framework flag asks for it (then NCHW data is transposed at the op
    # boundary — adjacent convs' transposes cancel under XLA, and the conv
    # itself runs in the MXU-preferred layout)
    internal_cl = channel_last or bool(_flags.conv_channels_last)
    strides = _norm_tuple(stride, n)
    dil = _norm_tuple(dilation, n)
    pad_cfg = _conv_padding(padding, n)

    if internal_cl:
        xs = tuple(_arr(x).shape)
        ws = tuple(_arr(weight).shape)                 # [O, I/g, *k]
        xs_int = xs if channel_last else (xs[0],) + xs[2:] + (xs[1],)
        ws_int = ws[2:] + (ws[1], ws[0])               # HWIO
        dn = lax.conv_dimension_numbers(xs_int, ws_int, _conv_dn(n, True))
        to_cl = (0,) + tuple(builtins.range(2, 2 + n)) + (1,)
        to_cf = (0, n + 1) + tuple(builtins.range(1, n + 1))
        w_perm = tuple(builtins.range(2, 2 + n)) + (1, 0)
        # kernel transpose: hoisted + cached per-param when not
        # differentiated this call; otherwise in-graph (once per trace)
        cached_w = _cl_weight_cached(weight, w_perm)

        def fn(a, w, *b):
            if not channel_last:
                a = jnp.transpose(a, to_cl)
            if cached_w is None:
                w = jnp.transpose(w, w_perm)
            out = lax.conv_general_dilated(
                a, w, window_strides=strides, padding=pad_cfg,
                rhs_dilation=dil, dimension_numbers=dn,
                feature_group_count=groups)
            out = out.astype(a.dtype)
            if b:
                # bias add in the NHWC epilogue, before any exit transpose
                out = out + b[0].reshape((1,) * (out.ndim - 1) + (-1,))
            if not channel_last:
                out = jnp.transpose(out, to_cf)
            return out

        args = [x, cached_w if cached_w is not None else weight] \
            + ([bias] if bias is not None else [])
        return apply_op("conv%dd" % n, fn, args)

    dn = lax.conv_dimension_numbers(
        _arr(x).shape, _arr(weight).shape, _conv_dn(n, channel_last))

    def fn(a, w, *b):
        # NOTE: no preferred_element_type upcast — the TPU MXU accumulates
        # bf16 convs in f32 internally, and an explicit f32 preference makes
        # jax's conv vjp emit an f32-cotangent × bf16-weight transposed conv,
        # which lax rejects (dtype mismatch in the backward pass)
        out = lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad_cfg,
            rhs_dilation=dil, dimension_numbers=dn, feature_group_count=groups)
        out = out.astype(a.dtype)
        if b:
            bshape = [1] * out.ndim
            c_axis = out.ndim - 1 if channel_last else 1
            bshape[c_axis] = -1
            out = out + b[0].reshape(bshape)
        return out

    args = [x, weight] + ([bias] if bias is not None else [])
    return apply_op("conv%dd" % n, fn, args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NHC" if data_format == "NLC" else "NCH"
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 1, fmt)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """Reference: functional/conv.py conv2d → phi conv kernel; here one
    lax.conv_general_dilated, which XLA tiles onto the MXU."""
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


# inference BN-fold cache: (weight, stats, affine, bias) identity ->
# (folded kernel, shift). Same safety rules as _cl_weight_cached (nothing
# differentiated, no tracers, no static recording); the weight rides a
# weakref, the small per-channel vectors are pinned in the value so their
# ids cannot be recycled into a false hit.
_FOLD_CACHE: "dict[tuple, tuple]" = {}
_FOLD_CACHE_MAX = 256


def _fold_bn_cached(weight, bias, rm, rv, gamma, beta, epsilon, w_perm):
    import weakref
    from ..core import autograd as _autograd
    parts = [t for t in (weight, bias, rm, rv, gamma, beta) if t is not None]
    if not all(isinstance(t, Tensor) for t in parts):
        return None
    if _autograd.is_grad_enabled() and any(
            (not t.stop_gradient or t._node is not None) for t in parts):
        return None
    arrs = [t._data for t in parts]
    if any(isinstance(a, jax.core.Tracer) for a in arrs):
        return None
    if _static_recording_active():
        return None
    w = weight._data
    rest = tuple(arrs[1:])
    key = tuple(id(a) for a in arrs) + (float(epsilon), w_perm)
    hit = _FOLD_CACHE.get(key)
    if (hit is not None and hit[0]() is w
            and builtins.all(a is b for a, b in zip(hit[1], rest))):
        return hit[2]
    inv = lax.rsqrt(rv._data.astype(jnp.float32) + epsilon)
    scale = inv if gamma is None else gamma._data.astype(jnp.float32) * inv
    shift = -rm._data.astype(jnp.float32) * scale
    if bias is not None:
        shift = shift + bias._data.astype(jnp.float32) * scale
    if beta is not None:
        shift = shift + beta._data.astype(jnp.float32)
    w_f = w * scale.astype(w.dtype).reshape(-1, 1, 1, 1)
    if w_perm is not None:
        w_f = jnp.transpose(w_f, w_perm)
    for k in [k for k, (r, _, _) in _FOLD_CACHE.items() if r() is None]:
        del _FOLD_CACHE[k]
    if len(_FOLD_CACHE) >= _FOLD_CACHE_MAX:
        _FOLD_CACHE.pop(next(iter(_FOLD_CACHE)))
    try:
        _FOLD_CACHE[key] = (weakref.ref(w), rest, (w_f, shift))
    except TypeError:
        pass
    return (w_f, shift)


# epilogue activations XLA fuses onto the conv's MXU output
_EPILOGUE_ACTS = {
    None: lambda v: v,
    "identity": lambda v: v,
    "relu": lambda v: jnp.maximum(v, 0),
    "relu6": lambda v: jnp.clip(v, 0, 6),
    # exact erf form to match F.gelu's default (jax.nn.gelu defaults to
    # the tanh approximation, which would break unfused-path parity)
    "gelu": lambda v: jax.nn.gelu(v, approximate=False),
    "silu": jax.nn.silu,
    "hardswish": lambda v: v * jnp.clip(v + 3, 0, 6) / 6,
    "leaky_relu": jax.nn.leaky_relu,
}


def fused_conv_bn_act(x, weight, bias=None, running_mean=None,
                      running_var=None, bn_weight=None, bn_bias=None,
                      stride=1, padding=0, dilation=1, groups=1,
                      data_format="NCHW", training=False, momentum=0.9,
                      epsilon=1e-5, use_global_stats=None, act=None,
                      residual=None, name=None):
    """Conv2D + BatchNorm + residual-add + activation as ONE jit-visible op.

    Inference (and use_global_stats) mode folds the BN scale/shift into the
    conv kernel and bias — w' = w * gamma/sqrt(var+eps) over the out-channel
    axis, b' = beta + (b - mean) * gamma/sqrt(var+eps) — so the whole block
    is a single conv whose epilogue (bias, residual, act) XLA fuses onto the
    MXU output. Training mode keeps batch statistics but still emits conv →
    normalize → scale/shift → (+residual) → act inside one op, so nothing
    re-enters HBM between the conv and its epilogue. Running stats update
    eagerly exactly like `batch_norm` (skipped inside jit traces).

    `act`: None or one of "relu", "relu6", "gelu", "silu", "hardswish",
    "leaky_relu", "identity" (see _EPILOGUE_ACTS). `residual` is added
    pre-activation and must be in the same layout as `x`. Honors
    FLAGS_conv_channels_last like `conv2d`.
    """
    from ..core import flags as _flags
    n = 2
    channel_last = data_format in _CHANNEL_LAST_FORMATS
    internal_cl = channel_last or bool(_flags.conv_channels_last)
    strides = _norm_tuple(stride, n)
    dil = _norm_tuple(dilation, n)
    pad_cfg = _conv_padding(padding, n)
    act_fn = _EPILOGUE_ACTS[act]
    use_batch_stats = training and not use_global_stats
    if not use_batch_stats and (running_mean is None or running_var is None):
        raise ValueError("fused_conv_bn_act in inference mode needs "
                         "running_mean/running_var")

    xs = tuple(_arr(x).shape)
    ws = tuple(_arr(weight).shape)                     # [O, I/g, kh, kw]
    if internal_cl:
        xs_int = xs if channel_last else (xs[0],) + xs[2:] + (xs[1],)
        dn = lax.conv_dimension_numbers(
            xs_int, ws[2:] + (ws[1], ws[0]), _conv_dn(n, True))
    else:
        dn = lax.conv_dimension_numbers(xs, ws, _conv_dn(n, False))
    to_cl, to_cf, w_perm = (0, 2, 3, 1), (0, 3, 1, 2), (2, 3, 1, 0)
    # broadcast shape for per-channel terms in the INTERNAL layout
    bshape = (1, 1, 1, -1) if internal_cl else (1, -1, 1, 1)
    red_axes = (0, 1, 2) if internal_cl else (0, 2, 3)

    def conv(a, w):
        return lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad_cfg, rhs_dilation=dil,
            dimension_numbers=dn, feature_group_count=groups).astype(a.dtype)

    has = (bias is not None, bn_weight is not None, bn_bias is not None,
           residual is not None)

    def unpack(rest):
        i = 0
        cb = gamma = beta = res = rm = rv = None
        if has[0]:
            cb = rest[i]; i += 1
        if not use_batch_stats:
            rm, rv = rest[i], rest[i + 1]; i += 2
        if has[1]:
            gamma = rest[i]; i += 1
        if has[2]:
            beta = rest[i]; i += 1
        if has[3]:
            res = rest[i]; i += 1
        return cb, gamma, beta, res, rm, rv

    args = [x, weight] + ([bias] if has[0] else []) \
        + ([] if use_batch_stats else [running_mean, running_var]) \
        + ([bn_weight] if has[1] else []) + ([bn_bias] if has[2] else []) \
        + ([residual] if has[3] else [])

    if not use_batch_stats:
        folded = _fold_bn_cached(weight, bias, running_mean, running_var,
                                 bn_weight, bn_bias, epsilon,
                                 w_perm if internal_cl else None)
        if folded is not None:
            # eager-inference fast path: the folded kernel/shift are
            # computed ONCE per (weight, stats, affine) identity — a
            # serving loop pays only the conv + epilogue per call
            w_f, shift = folded

            def ffn(a, wf, sh, *res):
                if internal_cl and not channel_last:
                    a = jnp.transpose(a, to_cl)
                    res = tuple(jnp.transpose(r, to_cl) for r in res)
                out = conv(a, wf) + sh.astype(a.dtype).reshape(bshape)
                if res:
                    out = out + res[0]
                out = act_fn(out).astype(a.dtype)
                if internal_cl and not channel_last:
                    out = jnp.transpose(out, to_cf)
                return out
            return apply_op("fused_conv_bn_act", ffn,
                            [x, w_f, shift]
                            + ([residual] if has[3] else []))

        def fn(a, w, *rest):
            cb, gamma, beta, res, rm, rv = unpack(rest)
            inv = lax.rsqrt(rv.astype(jnp.float32) + epsilon)
            scale = (inv if gamma is None
                     else gamma.astype(jnp.float32) * inv)      # [O]
            shift = -rm.astype(jnp.float32) * scale
            if cb is not None:
                shift = shift + cb.astype(jnp.float32) * scale
            if beta is not None:
                shift = shift + beta.astype(jnp.float32)
            w_f = w * scale.astype(w.dtype).reshape(-1, 1, 1, 1)  # fold [O]
            if internal_cl:
                w_f = jnp.transpose(w_f, w_perm)
                if not channel_last:
                    a = jnp.transpose(a, to_cl)
                    if res is not None:
                        res = jnp.transpose(res, to_cl)
            out = conv(a, w_f) + shift.astype(a.dtype).reshape(bshape)
            if res is not None:
                out = out + res
            out = act_fn(out).astype(a.dtype)
            if internal_cl and not channel_last:
                out = jnp.transpose(out, to_cf)
            return out
        return apply_op("fused_conv_bn_act", fn, args)

    def fn(a, w, *rest):
        cb, gamma, beta, res, _, _ = unpack(rest)
        if internal_cl:
            w = jnp.transpose(w, w_perm)
            if not channel_last:
                a = jnp.transpose(a, to_cl)
                if res is not None:
                    res = jnp.transpose(res, to_cl)
        y = conv(a, w)
        if cb is not None:
            y = y + cb.reshape(bshape)
        mu = y.mean(axis=red_axes, keepdims=True)
        var = y.var(axis=red_axes, keepdims=True)
        out = (y - mu) * lax.rsqrt(var + epsilon)
        if gamma is not None:
            out = out * gamma.reshape(bshape)
        if beta is not None:
            out = out + beta.reshape(bshape)
        if res is not None:
            out = out + res
        out = act_fn(out).astype(a.dtype)
        if internal_cl and not channel_last:
            out = jnp.transpose(out, to_cf)
        return out, mu.reshape(-1), var.reshape(-1)

    out, bm, bv = apply_op("fused_conv_bn_act", fn, args, n_outputs=3)
    # eager running-stat side effect, identical to batch_norm's (the batch
    # stats ride out of the op as extra outputs so the conv output never
    # materializes outside it); skipped under jit/static tracing
    if running_mean is not None and isinstance(bm, Tensor):
        m = bm._data
        if not isinstance(m, (jax.ShapeDtypeStruct, jax.core.Tracer)):
            rm_d, rv_d = running_mean._data, running_var._data
            running_mean._data = momentum * rm_d + \
                (1 - momentum) * m.astype(rm_d.dtype)
            running_var._data = momentum * rv_d + \
                (1 - momentum) * bv._data.astype(rv_d.dtype)
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCHW", output_size=None, name=None):
    """Reference: functional/conv.py conv2d_transpose. Implemented as the
    gradient of conv2d (lax.conv_transpose), weight layout [in, out/groups, kh, kw]."""
    strides = _norm_tuple(stride, 2)
    dil = _norm_tuple(dilation, 2)
    pad = _norm_tuple(padding, 2) if not isinstance(padding, str) else padding
    out_pad = _norm_tuple(output_padding, 2)

    def fn(a, w, *b):
        # lax.conv_transpose with IOHW spec: transpose weight [I,O,kh,kw]
        kh, kw = w.shape[2], w.shape[3]
        if isinstance(pad, str):
            padding_cfg = pad.upper()
        else:
            padding_cfg = [
                (dil[i] * (k - 1) - pad[i], dil[i] * (k - 1) - pad[i] + out_pad[i])
                for i, k in enumerate((kh, kw))
            ]
        if groups == 1:
            out = lax.conv_transpose(
                a, w, strides=strides, padding=padding_cfg,
                rhs_dilation=dil, dimension_numbers=("NCHW", "OIHW", "NCHW"),
                transpose_kernel=True)
        else:
            xs = jnp.split(a, groups, axis=1)
            ws = jnp.split(w, groups, axis=0)
            out = jnp.concatenate([
                lax.conv_transpose(xi, wi, strides=strides, padding=padding_cfg,
                                   rhs_dilation=dil,
                                   dimension_numbers=("NCHW", "OIHW", "NCHW"),
                                   transpose_kernel=True)
                for xi, wi in zip(xs, ws)], axis=1)
        if b:
            out = out + b[0].reshape(1, -1, 1, 1)
        return out

    args = [x, weight] + ([bias] if bias is not None else [])
    return apply_op("conv2d_transpose", fn, args)


# ----------------------------------------------------------------- pooling
def _pool(x, kernel, stride, padding, n, reducer, init, data_format="NCHW",
          ceil_mode=False, count_include_pad=True, exclusive=True):
    k = _norm_tuple(kernel, n)
    s = _norm_tuple(stride if stride is not None else kernel, n)
    p = _conv_padding(padding, n)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    if channel_last:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        spatial = builtins.range(1, 1 + n)
    else:
        dims = (1, 1) + k
        strides = (1, 1) + s
        spatial = builtins.range(2, 2 + n)
    if isinstance(p, str):
        pads = p
    else:
        full = [(0, 0)] * _arr(x).ndim
        for i, ax in enumerate(spatial):
            full[ax] = p[i]
        pads = full

    def fn(a):
        out = lax.reduce_window(a, init(a.dtype), reducer, dims, strides,
                                pads if isinstance(pads, list) else pads)
        return out
    return fn, dims, strides, pads, spatial


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    if return_mask:
        # segnet-style pool/unpool pair: non-overlapping windows
        st = stride if stride is not None else kernel_size
        if data_format != "NCHW":
            raise NotImplementedError("return_mask supports NCHW only")
        if _norm_tuple(st, 2) != _norm_tuple(kernel_size, 2) or padding != 0:
            raise NotImplementedError(
                "return_mask supports the unpool case: stride == "
                "kernel_size, padding 0")
        return apply_op("max_pool2d_with_index",
                        _max_pool_with_index(x, kernel_size, 2), [x],
                        n_outputs=2)
    fn, *_ = _pool(x, kernel_size, stride, padding, 2, lax.max,
                   lambda dt: -jnp.inf if jnp.issubdtype(dt, jnp.floating) else jnp.iinfo(dt).min,
                   data_format)
    return apply_op("max_pool2d", fn, [x])


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    fn, *_ = _pool(x, kernel_size, stride, padding, 1, lax.max,
                   lambda dt: -jnp.inf, "NCL")
    return apply_op("max_pool1d", fn, [x])


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    k = _norm_tuple(kernel_size, 2)
    fn_sum, dims, strides, pads, spatial = _pool(
        x, kernel_size, stride, padding, 2, lax.add, lambda dt: jnp.array(0, dt), data_format)

    def fn(a):
        ssum = lax.reduce_window(a, jnp.array(0, a.dtype), lax.add, dims, strides, pads)
        if divisor_override:
            return ssum / divisor_override
        if exclusive and pads != "VALID" and not isinstance(pads, str):
            ones = jnp.ones(a.shape, a.dtype)
            cnt = lax.reduce_window(ones, jnp.array(0, a.dtype), lax.add, dims, strides, pads)
            return ssum / cnt
        return ssum / math.prod(k)
    return apply_op("avg_pool2d", fn, [x])


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    k = _norm_tuple(kernel_size, 1)
    fn_sum, dims, strides, pads, spatial = _pool(
        x, kernel_size, stride, padding, 1, lax.add, lambda dt: jnp.array(0, dt), "NCL")

    def fn(a):
        ssum = lax.reduce_window(a, jnp.array(0, a.dtype), lax.add, dims, strides, pads)
        return ssum / math.prod(k)
    return apply_op("avg_pool1d", fn, [x])


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    out_hw = _norm_tuple(output_size, 2)
    channel_last = data_format == "NHWC"

    def fn(a):
        h, w = (a.shape[1], a.shape[2]) if channel_last \
            else (a.shape[-2], a.shape[-1])
        oh, ow = out_hw
        if h % oh == 0 and w % ow == 0:
            if channel_last:
                a2 = a.reshape(a.shape[0], oh, h // oh, ow, w // ow,
                               a.shape[-1])
                return a2.mean(axis=(2, 4))
            a2 = a.reshape(*a.shape[:-2], oh, h // oh, ow, w // ow)
            return a2.mean(axis=(-3, -1))
        # general case: interpolate bin edges (NCHW coordinates)
        if channel_last:
            a = jnp.moveaxis(a, -1, 1)
        rows = [(int(np.floor(i * h / oh)), int(np.ceil((i + 1) * h / oh))) for i in builtins.range(oh)]
        cols = [(int(np.floor(j * w / ow)), int(np.ceil((j + 1) * w / ow))) for j in builtins.range(ow)]
        parts = []
        for (r0, r1) in rows:
            row_parts = [a[..., r0:r1, c0:c1].mean(axis=(-2, -1)) for (c0, c1) in cols]
            parts.append(jnp.stack(row_parts, axis=-1))
        out = jnp.stack(parts, axis=-2)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply_op("adaptive_avg_pool2d", fn, [x])


def adaptive_avg_pool1d(x, output_size, name=None):
    o = output_size if isinstance(output_size, int) else output_size[0]

    def fn(a):
        l = a.shape[-1]
        if l % o == 0:
            return a.reshape(*a.shape[:-1], o, l // o).mean(axis=-1)
        edges = [(int(np.floor(i * l / o)), int(np.ceil((i + 1) * l / o))) for i in builtins.range(o)]
        return jnp.stack([a[..., s:e].mean(axis=-1) for s, e in edges], axis=-1)
    return apply_op("adaptive_avg_pool1d", fn, [x])


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out_hw = _norm_tuple(output_size, 2)

    def fn(a):
        h, w = a.shape[-2], a.shape[-1]
        oh, ow = out_hw
        if h % oh == 0 and w % ow == 0:
            a2 = a.reshape(*a.shape[:-2], oh, h // oh, ow, w // ow)
            return a2.max(axis=(-3, -1))
        rows = [(int(np.floor(i * h / oh)), int(np.ceil((i + 1) * h / oh))) for i in builtins.range(oh)]
        cols = [(int(np.floor(j * w / ow)), int(np.ceil((j + 1) * w / ow))) for j in builtins.range(ow)]
        parts = []
        for (r0, r1) in rows:
            parts.append(jnp.stack([a[..., r0:r1, c0:c1].max(axis=(-2, -1)) for (c0, c1) in cols], axis=-1))
        return jnp.stack(parts, axis=-2)
    return apply_op("adaptive_max_pool2d", fn, [x])


# ----------------------------------------------------------------- activations
def relu6(x, name=None):
    return apply_op("relu6", lambda a: jnp.clip(a, 0, 6), [x])


def gelu(x, approximate=False, name=None):
    return apply_op("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), [x])


def silu(x, name=None):
    return apply_op("silu", jax.nn.silu, [x])


def swish(x, name=None):
    return silu(x)


def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda a: jax.nn.elu(a, alpha=alpha), [x])


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), [x])


def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda a: jax.nn.celu(a, alpha=alpha), [x])


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope=negative_slope), [x])


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        c_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        shape[c_axis] = -1
        return jnp.where(a > 0, a, w.reshape(shape) * a)
    return apply_op("prelu", fn, [x, weight])


def hardshrink(x, threshold=0.5, name=None):
    return apply_op("hardshrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0), [x])


def softshrink(x, threshold=0.5, name=None):
    return apply_op("softshrink",
                    lambda a: jnp.where(a > threshold, a - threshold,
                                        jnp.where(a < -threshold, a + threshold, 0)), [x])


def tanhshrink(x, name=None):
    return apply_op("tanhshrink", lambda a: a - jnp.tanh(a), [x])


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return apply_op("hardtanh", lambda a: jnp.clip(a, min, max), [x])


def hardsigmoid(x, slope=1 / 6, offset=0.5, name=None):
    return apply_op("hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), [x])


def hardswish(x, name=None):
    return apply_op("hardswish", lambda a: a * jnp.clip(a + 3, 0, 6) / 6, [x])


def mish(x, name=None):
    return apply_op("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), [x])


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_op("softplus",
                    lambda a: jnp.where(beta * a > threshold, a,
                                        jax.nn.softplus(beta * a) / beta), [x])


def softsign(x, name=None):
    return apply_op("softsign", jax.nn.soft_sign, [x])


def glu(x, axis=-1, name=None):
    def fn(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return apply_op("glu", fn, [x])


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    g = -jnp.log(-jnp.log(
        jax.random.uniform(_random.split_key(), tuple(_arr(x).shape), minval=1e-20, maxval=1.0)))

    def fn(a):
        y = jax.nn.softmax((a + g.astype(a.dtype)) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis)
            y_hard = jax.nn.one_hot(idx, y.shape[axis], axis=axis, dtype=y.dtype)
            y = y_hard + y - lax.stop_gradient(y)  # straight-through estimator
        return y
    return apply_op("gumbel_softmax", fn, [x])


# ----------------------------------------------------------------- dropout
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    """Reference: functional/common.py dropout; phi dropout kernel semantics
    (upscale_in_train = inverted dropout)."""
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply_op("dropout_scale", lambda a: a * (1 - p), [x])
        return x
    if p == 1.0:
        return apply_op("dropout", lambda a: jnp.zeros_like(a), [x])
    axes = None if axis is None else ((axis,) if isinstance(axis, int) else tuple(axis))
    key = _random.op_key()  # symbolic per-run key under static recording

    def fn(a, k):
        # mask shape derived from the runtime array (not the build-time
        # shape): under static mode with a -1 batch dim the recorded shape is
        # a placeholder, and the mask must still be per-row independent
        mshape = (a.shape if axes is None
                  else tuple(s if i in axes else 1 for i, s in enumerate(a.shape)))
        keep = jax.random.bernoulli(k, 1.0 - p, mshape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return apply_op("dropout", fn, [x, key])


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    neg_sat = -alpha * scale
    a_coef = (1.0 / math.sqrt((1 - p) * (1 + p * neg_sat ** 2)))
    b_coef = -a_coef * p * neg_sat
    key = _random.op_key()

    def fn(a, k):
        keep = jax.random.bernoulli(k, 1.0 - p, a.shape)
        out = jnp.where(keep, a, neg_sat)
        return (a_coef * out + b_coef).astype(a.dtype)
    return apply_op("alpha_dropout", fn, [x, key])


# ----------------------------------------------------------------- norms
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))
    axes = tuple(builtins.range(_arr(x).ndim - n_axes, _arr(x).ndim))

    if n_axes == 1:
        # opt-in Pallas fused LN (PADDLE_TPU_FUSED_LN=1): single HBM pass
        # per direction. Measured neutral-to-slower than XLA's autodiff on
        # the v5e bench chip (see ops/pallas/layer_norm.py docstring), so
        # the XLA formulation stays the default.
        from ..ops.pallas.layer_norm import (fused_layer_norm,
                                             fused_layer_norm_supported)
        if fused_layer_norm_supported(tuple(_arr(x).shape)):
            def ffn(a, *wb):
                i = 0
                g = bb = None
                if weight is not None:
                    g = wb[i]; i += 1
                if bias is not None:
                    bb = wb[i]
                return fused_layer_norm(a, g, bb, eps=epsilon)
            args = [x] + [t for t in (weight, bias) if t is not None]
            return apply_op("layer_norm", ffn, args)

    def fn(a, *wb):
        mu = a.mean(axis=axes, keepdims=True)
        var = ((a - mu) ** 2).mean(axis=axes, keepdims=True)
        out = (a - mu) * lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out.astype(a.dtype)

    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("layer_norm", fn, args)


def rms_norm(x, weight=None, epsilon=1e-6, axis=-1, name=None):
    """RMSNorm — beyond-reference op needed by modern LLM families."""
    def fn(a, *w):
        dt = a.dtype
        a32 = a.astype(jnp.float32)
        ms = jnp.mean(a32 * a32, axis=axis, keepdims=True)
        out = a32 * lax.rsqrt(ms + epsilon)
        if w:
            out = out * w[0].astype(jnp.float32)
        return out.astype(dt)
    args = [x] + ([weight] if weight is not None else [])
    return apply_op("rms_norm", fn, args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None, name=None):
    """Reference: functional/norm.py batch_norm. In training mode the running
    stats are updated in place on the provided buffer tensors (host-side
    assignment, XLA-functional under the hood)."""
    c_axis = 1 if data_format.startswith("NC") else _arr(x).ndim - 1
    reduce_axes = tuple(i for i in builtins.range(_arr(x).ndim) if i != c_axis)
    bshape = [1] * _arr(x).ndim
    bshape[c_axis] = -1

    use_batch_stats = training and not use_global_stats
    if use_batch_stats:
        import jax as _jax
        # running-stat updates are an EAGER side effect (paddle semantics);
        # under jit/static tracing the value is symbolic — skip the update
        # rather than leak a tracer into the buffer
        _xv = _arr(x)
        if not isinstance(_xv, (_jax.ShapeDtypeStruct, _jax.core.Tracer)):
            batch_mean = jnp.mean(_arr(x), axis=reduce_axes)
            batch_var = jnp.var(_arr(x), axis=reduce_axes)
            if running_mean is not None:
                running_mean._data = momentum * running_mean._data + (1 - momentum) * batch_mean
                running_var._data = momentum * running_var._data + (1 - momentum) * batch_var

    def fn(a, *rest):
        j = 0
        if use_batch_stats:
            mu = a.mean(axis=reduce_axes, keepdims=True)
            var = a.var(axis=reduce_axes, keepdims=True)
        else:
            mu = rest[0].reshape(bshape)
            var = rest[1].reshape(bshape)
            j = 2
        out = (a - mu) * lax.rsqrt(var + epsilon)
        if weight is not None:
            out = out * rest[j].reshape(bshape)
            j += 1
        if bias is not None:
            out = out + rest[j].reshape(bshape)
        return out.astype(a.dtype)

    args = [x] + ([] if use_batch_stats else [running_mean, running_var]) \
        + [t for t in (weight, bias) if t is not None]
    return apply_op("batch_norm", fn, args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    def fn(a, *wb):
        n, c = a.shape[0], a.shape[1]
        rest = a.shape[2:]
        g = a.reshape(n, num_groups, c // num_groups, *rest)
        axes = tuple(builtins.range(2, g.ndim))
        mu = g.mean(axis=axes, keepdims=True)
        var = g.var(axis=axes, keepdims=True)
        out = ((g - mu) * lax.rsqrt(var + epsilon)).reshape(a.shape)
        bshape = [1, c] + [1] * len(rest)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        return out.astype(a.dtype)
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("group_norm", fn, args)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    def fn(a, *wb):
        axes = tuple(builtins.range(2, a.ndim))
        mu = a.mean(axis=axes, keepdims=True)
        var = a.var(axis=axes, keepdims=True)
        out = (a - mu) * lax.rsqrt(var + eps)
        bshape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        return out.astype(a.dtype)
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("instance_norm", fn, args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def fn(a):
        sq = a * a
        half = size // 2
        c = a.shape[1]
        pads = [(0, 0)] * a.ndim
        pads[1] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        acc = jnp.zeros_like(a)
        for i in builtins.range(size):
            acc = acc + lax.dynamic_slice_in_dim(padded, i, c, axis=1)
        return a / jnp.power(k + alpha * acc / size, beta)
    return apply_op("local_response_norm", fn, [x])


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(a):
        nrm = jnp.linalg.norm(a, ord=p, axis=axis, keepdims=True)
        return a / jnp.maximum(nrm, epsilon)
    return apply_op("normalize", fn, [x])


# ----------------------------------------------------------------- losses
def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    """Reference: functional/loss.py cross_entropy → phi
    softmax_with_cross_entropy kernel. Stable log_softmax + gather; on TPU the
    whole thing fuses into a couple of VPU passes."""
    def fn(logits, lbl, *wargs):
        w = wargs[0] if wargs else None
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-30, None))
        n_classes = logits.shape[axis]
        if soft_label:
            tgt = lbl.astype(jnp.float32)
            if label_smoothing > 0:
                tgt = tgt * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -(tgt * logp).sum(axis=axis)
            if reduction == "none":
                return loss
            return _reduce_loss(loss, reduction)
        idx = lbl.astype(jnp.int32)
        squeeze = False
        if idx.ndim == logp.ndim:  # [..., 1] labels
            idx = jnp.squeeze(idx, axis=axis)
            squeeze = True
        safe_idx = jnp.where(idx == ignore_index, 0, idx)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe_idx, axis), axis=axis)
        picked = jnp.squeeze(picked, axis=axis)
        if label_smoothing > 0:
            smooth = logp.mean(axis=axis)
            nll = -(1 - label_smoothing) * picked - label_smoothing * smooth
        else:
            nll = -picked
        valid = (idx != ignore_index)
        nll = jnp.where(valid, nll, 0.0)
        if w is not None:
            ww = jnp.take(w, safe_idx)
            nll = nll * jnp.where(valid, ww, 0.0)
            if reduction == "mean":
                denom = jnp.sum(jnp.where(valid, ww, 0.0))
                return jnp.sum(nll) / jnp.maximum(denom, 1e-12)
        if reduction == "mean":
            denom = jnp.maximum(valid.sum(), 1)
            return jnp.sum(nll) / denom
        return _reduce_loss(nll, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op("cross_entropy", fn, args)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index,
                         reduction="none", axis=axis)
    from ..core.ops import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):  # noqa: A002
    def fn(logp, lbl, *wargs):
        w = wargs[0] if wargs else None
        idx = lbl.astype(jnp.int32)
        safe = jnp.where(idx == ignore_index, 0, idx)
        picked = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0] if logp.ndim == idx.ndim + 1 \
            else jnp.take_along_axis(logp, safe[:, None], axis=1)[:, 0]
        nll = -picked
        valid = idx != ignore_index
        nll = jnp.where(valid, nll, 0.0)
        if w is not None:
            ww = jnp.take(w, safe)
            nll = nll * jnp.where(valid, ww, 0.0)
            if reduction == "mean":
                return jnp.sum(nll) / jnp.maximum(jnp.sum(jnp.where(valid, ww, 0.0)), 1e-12)
        if reduction == "mean":
            return jnp.sum(nll) / jnp.maximum(valid.sum(), 1)
        return _reduce_loss(nll, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op("nll_loss", fn, args)


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply_op("mse_loss", lambda a, b: _reduce_loss((a - b) ** 2, reduction), [input, label])


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply_op("l1_loss", lambda a, b: _reduce_loss(jnp.abs(a - b), reduction), [input, label])


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def fn(a, b):
        d = a - b
        loss = jnp.where(jnp.abs(d) < delta, 0.5 * d * d / delta, jnp.abs(d) - 0.5 * delta)
        return _reduce_loss(loss, reduction)
    return apply_op("smooth_l1_loss", fn, [input, label])


def kl_div(input, label, reduction="mean", name=None):  # noqa: A002
    def fn(logp, tgt):
        loss = tgt * (jnp.log(jnp.clip(tgt, 1e-30, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce_loss(loss, reduction)
    return apply_op("kl_div", fn, [input, label])


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    def fn(p, t, *w):
        loss = -(t * jnp.log(jnp.clip(p, 1e-12, None)) +
                 (1 - t) * jnp.log(jnp.clip(1 - p, 1e-12, None)))
        if w:
            loss = loss * w[0]
        return _reduce_loss(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op("bce", fn, args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def fn(z, t, *rest):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]; i += 1
        log_sig = jax.nn.log_sigmoid(z)
        log_one_minus = jax.nn.log_sigmoid(-z)
        if pw is not None:
            loss = -(pw * t * log_sig + (1 - t) * log_one_minus)
        else:
            loss = -(t * log_sig + (1 - t) * log_one_minus)
        if w is not None:
            loss = loss * w
        return _reduce_loss(loss, reduction)
    args = [logit, label] + [t for t in (weight, pos_weight) if t is not None]
    return apply_op("bce_with_logits", fn, args)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):  # noqa: A002
    def fn(a, b, y):
        loss = jnp.maximum(0, -y * (a - b) + margin)
        return _reduce_loss(loss, reduction)
    return apply_op("margin_ranking_loss", fn, [input, other, label])


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        dot = (a * b).sum(axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)
    return apply_op("cosine_similarity", fn, [x1, x2])


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, y):
        cos = (a * b).sum(axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0, cos - margin))
        return _reduce_loss(loss, reduction)
    return apply_op("cosine_embedding_loss", fn, [input1, input2, label])


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    def fn(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0, margin - a))
        return _reduce_loss(loss, reduction)
    return apply_op("hinge_embedding_loss", fn, [input, label])


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,  # noqa: A002
                        swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce_loss(jnp.maximum(dp - dn + margin, 0), reduction)
    return apply_op("triplet_margin_loss", fn, [input, positive, negative])


def square_error_cost(input, label, name=None):  # noqa: A002
    return apply_op("square_error_cost", lambda a, b: (a - b) ** 2, [input, label])


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    def fn(p, t):
        return -t * jnp.log(p + epsilon) - (1 - t) * jnp.log(1 - p + epsilon)
    return apply_op("log_loss", fn, [input, label])


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, t, *nrm):
        p = jax.nn.sigmoid(z)
        ce = -(t * jax.nn.log_sigmoid(z) + (1 - t) * jax.nn.log_sigmoid(-z))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if nrm:
            loss = loss / nrm[0]
        return _reduce_loss(loss, reduction)
    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return apply_op("sigmoid_focal_loss", fn, args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Reference: functional/loss.py ctc_loss (warpctc op). Implemented with
    the standard alpha-recursion in log space via lax.scan."""
    lp = _arr(log_probs)  # [T, B, C] paddle layout
    lab = _arr(labels)    # [B, L]
    in_len = _arr(input_lengths)
    lab_len = _arr(label_lengths)

    def fn(lp_):
        T, B, C = lp_.shape
        L = lab.shape[1]
        S = 2 * L + 1
        # extended label seq: blank, l1, blank, l2, ... blank
        ext = jnp.full((B, S), blank, dtype=lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        neg_inf = jnp.float32(-1e30)
        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp_[0, jnp.arange(B), blank])
        first_lab = lp_[0, jnp.arange(B), ext[:, 1]]
        alpha0 = alpha0.at[:, 1].set(jnp.where(L > 0, first_lab, neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a_shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, merged + emit

        alphas_last, alphas = lax.scan(step, alpha0, lp_[1:])
        all_alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, S]
        t_idx = jnp.clip(in_len - 1, 0, T - 1)
        final = all_alphas[t_idx, jnp.arange(B)]  # [B, S]
        end1 = jnp.take_along_axis(final, (2 * lab_len)[:, None], axis=1)[:, 0]
        end2 = jnp.take_along_axis(final, jnp.clip(2 * lab_len - 1, 0, S - 1)[:, None], axis=1)[:, 0]
        ll = jnp.logaddexp(end1, jnp.where(lab_len > 0, end2, neg_inf))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len, 1))
        return _reduce_loss(loss, reduction)
    return apply_op("ctc_loss", fn, [log_probs])


# ----------------------------------------------------------------- attention
def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None,
                                 score_dtype=None):
    """Fused attention entry point. [B, S, H, D] layout (paddle convention).

    Uses the Pallas flash-attention kernel on TPU when shapes allow (see
    paddle_tpu/ops/pallas/flash_attention.py), else a reference jnp path —
    beyond the reference snapshot, which has no flash attention (SURVEY §5.7).
    score_dtype (beyond-reference knob): storage dtype for the S×S
    logits/probs on the non-flash path; pass the model dtype (bf16) to
    halve its O(S²) HBM traffic — f32 accumulation is kept either way.
    """
    from ..ops import attention as _attn
    return _attn.scaled_dot_product_attention(
        query, key, value, attn_mask=attn_mask, dropout_p=dropout_p,
        is_causal=is_causal, training=training, score_dtype=score_dtype)


# ----------------------------------------------------------------- misc
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    from ..core.ops import pad as _pad
    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(t, *pd):
        n = t.shape[-1]
        if pd:
            return (1 - epsilon) * t + epsilon * pd[0]
        return (1 - epsilon) * t + epsilon / n
    args = [label] + ([prior_dist] if prior_dist is not None else [])
    return apply_op("label_smooth", fn, args)


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    if maxlen is None:  # data-dependent width: eager host read
        maxlen = int(np.asarray(_arr(lengths)).max())
    m = int(maxlen)
    def fn(ln):
        return (jnp.arange(m)[None, :] < ln[..., None]).astype(convert_dtype(dtype))
    return apply_op("sequence_mask", fn, [lengths])


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    """Reference: functional/common.py interpolate (phi interpolate kernels).
    nearest & (bi)linear supported on NCHW/NCL."""
    a = _arr(x)
    spatial_ndim = a.ndim - 2
    if size is not None:
        out_size = _norm_tuple(size, spatial_ndim)
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * spatial_ndim
        out_size = tuple(int(a.shape[2 + i] * sf[i]) for i in builtins.range(spatial_ndim))

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def fn(arr):
        out_shape = (*arr.shape[:2], *out_size)
        return jax.image.resize(arr, out_shape, method=jmode)
    return apply_op("interpolate", fn, [x])


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(a):
        n, c, h, w = a.shape
        oc = c // (r * r)
        out = a.reshape(n, oc, r, r, h, w)
        out = out.transpose(0, 1, 4, 2, 5, 3)
        return out.reshape(n, oc, h * r, w * r)
    return apply_op("pixel_shuffle", fn, [x])


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: functional/common.py unfold)."""
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    p = _norm_tuple(paddings, 2)
    d = _norm_tuple(dilations, 2)

    def fn(a):
        n, c, h, w = a.shape
        a_p = jnp.pad(a, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        oh = (h + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (w + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        cols = []
        for i in builtins.range(k[0]):
            for j in builtins.range(k[1]):
                patch = a_p[:, :, i * d[0]: i * d[0] + oh * s[0]: s[0],
                            j * d[1]: j * d[1] + ow * s[1]: s[1]]
                cols.append(patch)
        out = jnp.stack(cols, axis=2)  # [N, C, k*k, oh, ow]
        return out.reshape(n, c * k[0] * k[1], oh * ow)
    return apply_op("unfold", fn, [x])


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def fn(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]), v[:, :-1, fold:2 * fold]], axis=1)
        rest = v[:, :, 2 * fold:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
    return apply_op("temporal_shift", fn, [x])


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    def fn(a, g):
        n, c, h, w = a.shape
        gx = (g[..., 0] + 1) * (w - 1) / 2 if align_corners else ((g[..., 0] + 1) * w - 1) / 2
        gy = (g[..., 1] + 1) * (h - 1) / 2 if align_corners else ((g[..., 1] + 1) * h - 1) / 2
        x0 = jnp.floor(gx); y0 = jnp.floor(gy)
        x1, y1 = x0 + 1, y0 + 1
        wx1 = gx - x0; wx0 = 1 - wx1
        wy1 = gy - y0; wy0 = 1 - wy1

        def sample(yy, xx):
            valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            vals = a[jnp.arange(n)[:, None, None], :, yc, xc]  # [N, gh, gw, C]
            return jnp.where(valid[..., None], vals, 0.0)

        out = (sample(y0, x0) * (wy0 * wx0)[..., None] +
               sample(y0, x1) * (wy0 * wx1)[..., None] +
               sample(y1, x0) * (wy1 * wx0)[..., None] +
               sample(y1, x1) * (wy1 * wx1)[..., None])
        return jnp.moveaxis(out, -1, 1)
    return apply_op("grid_sample", fn, [x, grid])


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def fn(th):
        n, c, h, w = out_shape
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [h, w, 3]
        return jnp.einsum("hwk,nok->nhwo", base, th)
    return apply_op("affine_grid", fn, [theta])


# ---------------------------------------------------------------------------
# Surface-completion batch (reference: python/paddle/nn/functional/__init__.py
# parity). Activations / paddings / shape ops.

def log_sigmoid(x, name=None):
    return apply_op("log_sigmoid", jax.nn.log_sigmoid, [x])


def thresholded_relu(x, threshold=1.0, name=None):
    return apply_op("thresholded_relu",
                    lambda a: jnp.where(a > threshold, a, 0.0).astype(a.dtype),
                    [x])


def maxout(x, groups, axis=1, name=None):
    """reference: maxout_op — out channel c = max over the CONSECUTIVE
    input channels [c*groups, (c+1)*groups) (phi maxouting.cc:47 index
    in_c = c*groups + ph)."""
    def fn(a):
        ax = axis if axis >= 0 else a.ndim + axis
        c = a.shape[ax]
        if c % groups:
            raise ValueError(f"channels {c} not divisible by groups {groups}")
        shp = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return a.reshape(shp).max(axis=ax + 1)
    return apply_op("maxout", fn, [x])


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    """reference: rrelu_op — random leaky slope in train, mean slope in eval."""
    if training:
        key = _random.split_key()

        def fn(a):
            slope = jax.random.uniform(key, a.shape, jnp.float32, lower, upper)
            return jnp.where(a >= 0, a, a * slope.astype(a.dtype))
    else:
        mid = (lower + upper) / 2.0

        def fn(a):
            return jnp.where(a >= 0, a, a * mid).astype(a.dtype)
    return apply_op("rrelu", fn, [x])


def relu_(x, name=None):
    return x._replace(relu(x))


def elu_(x, alpha=1.0, name=None):
    return x._replace(elu(x, alpha))


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._replace(softmax(x, axis=axis, dtype=dtype))


def tanh_(x, name=None):
    from ..core.ops import tanh as _tanh
    return x._replace(_tanh(x))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    p = _norm_tuple(padding, 4)  # [left, right, top, bottom]

    def fn(a):
        if data_format == "NCHW":
            pads = [(0, 0), (0, 0), (p[2], p[3]), (p[0], p[1])]
        else:
            pads = [(0, 0), (p[2], p[3]), (p[0], p[1]), (0, 0)]
        return jnp.pad(a, pads)
    return apply_op("zeropad2d", fn, [x])


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    """reference: pixel_unshuffle_op — space-to-depth (inverse of
    pixel_shuffle)."""
    r = int(downscale_factor)

    def fn(a):
        if data_format != "NCHW":
            a = jnp.moveaxis(a, -1, 1)
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = a.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r, h // r, w // r)
        if data_format != "NCHW":
            a = jnp.moveaxis(a, 1, -1)
        return a
    return apply_op("pixel_unshuffle", fn, [x])


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(a):
        if data_format != "NCHW":
            a = jnp.moveaxis(a, -1, 1)
        n, c, h, w = a.shape
        a = a.reshape(n, groups, c // groups, h, w)
        a = a.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        if data_format != "NCHW":
            a = jnp.moveaxis(a, 1, -1)
        return a
    return apply_op("channel_shuffle", fn, [x])


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """reference: diag_embed op — batched vector -> diagonal matrices."""
    def fn(a):
        n = a.shape[-1]
        m = n + builtins.abs(offset)
        base = jnp.zeros(a.shape[:-1] + (m, m), a.dtype)
        idx = jnp.arange(n)
        rows = idx if offset >= 0 else idx - offset
        cols = idx + offset if offset >= 0 else idx
        base = base.at[..., rows, cols].set(a)
        d1 = dim1 if dim1 >= 0 else base.ndim + dim1
        d2 = dim2 if dim2 >= 0 else base.ndim + dim2
        nd = base.ndim
        return jnp.moveaxis(base, (nd - 2, nd - 1),
                            (d1, d2) if d1 < d2 else (d2, d1))
    return apply_op("diag_embed", fn, [input])


def bilinear(x1, x2, weight, bias=None, name=None):
    """reference: bilinear op — out[n,o] = x1[n,:] W[o] x2[n,:] + b."""
    args = [x1, x2, weight] + ([bias] if bias is not None else [])

    def fn(a, b, w, *bb):
        out = jnp.einsum("ni,oij,nj->no", a, w, b)
        if bb:
            out = out + bb[0]
        return out
    return apply_op("bilinear", fn, args)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def fn(a, b):
        d = a - b + epsilon
        return jnp.linalg.norm(d.astype(jnp.float32), ord=p, axis=-1,
                               keepdims=keepdim).astype(a.dtype)
    return apply_op("pairwise_distance", fn, [x, y])


# ----------------------------------------------------------------- losses
# (_reduce_loss shared with the earlier loss section)

def soft_margin_loss(input, label, reduction="mean", name=None):
    def fn(x, y):
        # softplus(-y*x): overflow-stable form of log(1 + exp(-y*x))
        return _reduce_loss(jax.nn.softplus(-y.astype(x.dtype) * x),
                            reduction)
    return apply_op("soft_margin_loss", fn, [input, label])


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    args = [input, label] + ([weight] if weight is not None else [])

    def fn(x, y, *w):
        y = y.astype(x.dtype)
        loss = y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x)
        loss = -loss
        if w:
            loss = loss * w[0]
        return _reduce_loss(loss.mean(axis=-1), reduction)
    return apply_op("multi_label_soft_margin_loss", fn, args)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    args = [input, label] + ([weight] if weight is not None else [])

    def fn(x, y, *w):
        n, c = x.shape
        gold = jnp.take_along_axis(x, y[:, None].astype(jnp.int32), axis=1)
        diff = jnp.maximum(0.0, margin - gold + x)
        if p != 1:
            diff = diff ** p
        if w:
            diff = diff * jnp.take(w[0], y.astype(jnp.int32))[:, None]
        mask = jnp.arange(c)[None, :] != y[:, None]
        return _reduce_loss(jnp.where(mask, diff, 0.0).sum(axis=1) / c,
                            reduction)
    return apply_op("multi_margin_loss", fn, args)


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """reference: npair_loss (metric learning)."""
    def fn(a, p, y):
        sim = a @ p.T                                     # [n, n]
        y = y.reshape(-1)
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = same / same.sum(axis=1, keepdims=True)
        xent = jnp.mean(jax.nn.logsumexp(sim, axis=1) -
                        jnp.sum(sim * tgt, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1)) +
                        jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return xent + reg
    return apply_op("npair_loss", fn, [anchor, positive, labels])


def dice_loss(input, label, epsilon=1e-5, name=None):
    """reference: dice_loss (segmentation) — input prob [N,...,C], label
    int [N,...,1]."""
    def fn(x, y):
        nc = x.shape[-1]
        oh = jax.nn.one_hot(y.reshape(y.shape[:-1]).astype(jnp.int32), nc,
                            dtype=x.dtype)
        x2 = x.reshape(x.shape[0], -1)
        y2 = oh.reshape(oh.shape[0], -1)
        inter = (x2 * y2).sum(axis=1)
        union = x2.sum(axis=1) + y2.sum(axis=1)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply_op("dice_loss", fn, [input, label])


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    dist = distance_function or (lambda a, b: pairwise_distance(a, b))
    d_ap = dist(input, positive)
    d_an = dist(input, negative)
    if swap:
        d_pn = dist(positive, negative)
        from ..core.ops import minimum as _min
        d_an = _min(d_an, d_pn)

    def fn(ap, an):
        return _reduce_loss(jnp.maximum(0.0, ap - an + margin), reduction)
    return apply_op("triplet_margin_distance", fn, [d_ap, d_an])


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """reference: hierarchical_sigmoid op. Default (complete binary tree)
    path encoding over `num_classes` leaves."""
    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "custom-tree hsigmoid (path_table/path_code) is not supported; "
            "the default complete-tree mode matches the reference default")
    depth = builtins.max(1, int(np.ceil(np.log2(builtins.max(2, num_classes)))))
    # host-computed static code tables; leaves at uneven depth (num_classes
    # not a power of two) get shorter paths — valid[] masks padded steps
    codes = np.zeros((num_classes, depth), np.int64)     # inner-node index
    signs = np.zeros((num_classes, depth), np.float32)   # 0/1 branch bit
    valid = np.zeros((num_classes, depth), np.float32)
    for c in builtins.range(num_classes):
        node = c + num_classes  # leaf position in implicit heap
        d = 0
        while node > 1 and d < depth:
            parent = node // 2
            signs[c, depth - 1 - d] = float(node % 2)
            codes[c, depth - 1 - d] = parent - 1
            valid[c, depth - 1 - d] = 1.0
            node = parent
            d += 1
    args = [input, label, weight] + ([bias] if bias is not None else [])

    def fn(x, y, w, *b):
        yy = y.reshape(-1).astype(jnp.int32)
        node_idx = jnp.asarray(codes)[yy]                # [n, depth]
        bits = jnp.asarray(signs)[yy]                    # [n, depth]
        vmask = jnp.asarray(valid)[yy]
        wv = w[node_idx]                                 # [n, depth, dim]
        logits = jnp.einsum("nd,nkd->nk", x, wv)
        if b:
            logits = logits + b[0].reshape(-1)[node_idx]
        # P(bit) via sigmoid; loss = -sum log P over REAL path steps
        lp = bits * jax.nn.log_sigmoid(logits) + \
            (1 - bits) * jax.nn.log_sigmoid(-logits)
        return -(lp * vmask).sum(axis=1, keepdims=True)
    return apply_op("hsigmoid_loss", fn, args)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """reference: warprnnt_op — RNN-T transducer loss. Forward-variable
    (alpha) dynamic program over the [T, U] lattice as nested lax.scans,
    fully on-device and differentiable by jax AD (the reference backprops
    hand-written gradients; autodiff of the DP is the XLA-native way)."""
    def fn(logits, labels, t_len, u_len):
        # logits [B, T, U+1, V] log-probs expected (reference applies
        # log_softmax internally when needed)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        B, T, U1, V = lp.shape
        U = U1 - 1
        labels = labels.astype(jnp.int32)
        blank_lp = lp[..., blank]                               # [B,T,U+1]
        emit_lp = jnp.take_along_axis(
            lp[:, :, :U, :], labels[:, None, :, None], axis=3)[..., 0]
        # alpha recursion:
        #   alpha[t, u] = logaddexp(alpha[t-1, u] + blank(t-1, u),
        #                           alpha[t, u-1] + emit(t, u-1))
        # outer scan over t; inner scan builds the row left-to-right (the
        # u-1 dependency is sequential within a row)
        neg = jnp.float32(-1e30)
        bi_ = jnp.arange(B)

        def scan_t(alpha_prev, t):
            fb = alpha_prev + blank_lp[:, t - 1, :]             # [B, U+1]

            def scan_u(carry, u):
                v = jnp.where(u == 0, fb[:, 0],
                              jnp.logaddexp(fb[bi_, u],
                                            carry + emit_lp[bi_, t, u - 1]))
                return v, v
            _, cols = jax.lax.scan(scan_u, jnp.full((B,), neg),
                                   jnp.arange(U1))
            alpha_t = cols.T                                    # [B, U+1]
            return alpha_t, alpha_t

        # alpha[0, u]: pure emission chain at t=0
        def scan_u0(carry, u):
            v = jnp.where(u == 0, jnp.zeros((B,), jnp.float32),
                          carry + emit_lp[bi_, 0, u - 1])
            return v, v
        _, cols0 = jax.lax.scan(scan_u0, jnp.full((B,), neg), jnp.arange(U1))
        alpha0 = cols0.T

        _, alphas = jax.lax.scan(scan_t, alpha0, jnp.arange(1, T))
        all_alpha = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T,B,U+1]
        # ll = alpha[t_len-1, u_len] + blank(t_len-1, u_len)
        bi = jnp.arange(B)
        tl = t_len.astype(jnp.int32) - 1
        ul = u_len.astype(jnp.int32)
        final_alpha = all_alpha[tl, bi, ul]
        ll = final_alpha + blank_lp[bi, tl, ul]
        loss = -ll
        return _reduce_loss(loss, reduction)
    return apply_op("rnnt_loss", fn, [input, label, input_lengths,
                                      label_lengths])


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean", name=None):
    """reference: margin_cross_entropy op (ArcFace/CosFace family):
    cos(m1*theta + m2) - m3 margin on the gold logit, then scaled CE."""
    def fn(x, y):
        yy = y.reshape(-1).astype(jnp.int32)
        x32 = jnp.clip(x.astype(jnp.float32), -1.0, 1.0)
        theta = jnp.arccos(jnp.take_along_axis(x32, yy[:, None], axis=1))
        marg = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(yy, x.shape[-1], dtype=jnp.float32)
        logits_m = (x32 * (1 - onehot) + marg * onehot) * scale
        logp = jax.nn.log_softmax(logits_m, axis=-1)
        loss = -jnp.take_along_axis(logp, yy[:, None], axis=1)
        sm = jnp.exp(logp)
        return _reduce_loss(loss, reduction), sm
    loss, sm = apply_op("margin_cross_entropy", fn, [logits, label],
                        n_outputs=2)
    return (loss, sm) if return_softmax else loss


def class_center_sample(label, num_classes, num_samples, group=None, name=None):
    """reference: class_center_sample op (PartialFC) — sample the positive
    class centers plus random negatives; remap labels into the sampled set."""
    lab = np.asarray(label._data if isinstance(label, Tensor) else label,
                     np.int64).reshape(-1)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        rest = np.setdiff1d(np.arange(num_classes, dtype=np.int64), pos,
                            assume_unique=True)
        # negatives drawn from the package RNG stream: fresh per call,
        # reproducible under paddle.seed
        seed = int(jax.random.randint(_random.split_key(), (), 0, 2**31 - 1))
        extra = np.random.RandomState(seed).choice(
            rest, num_samples - len(pos), replace=False)
        sampled = np.concatenate([pos, np.sort(extra)])
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    remapped = remap[lab]
    return (Tensor(jnp.asarray(remapped), stop_gradient=True),
            Tensor(jnp.asarray(sampled), stop_gradient=True))


def gather_tree(ids, parents, name=None):
    """reference: gather_tree op — backtrace beam-search ancestry.
    ids/parents: [max_time, batch, beam]."""
    def fn(idv, par):
        tmax = idv.shape[0]
        beam = idv.shape[2]

        def step(carry, t):
            # carry: beam indices to follow at time t+1  [batch, beam]
            sel = carry
            out_t = jnp.take_along_axis(idv[t], sel, axis=1)
            nxt = jnp.take_along_axis(par[t], sel, axis=1)
            return nxt, out_t
        init = jnp.tile(jnp.arange(beam)[None, :], (idv.shape[1], 1))
        _, outs = jax.lax.scan(step, init, jnp.arange(tmax - 1, -1, -1))
        return outs[::-1]
    return apply_op("gather_tree", fn, [ids, parents])


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """reference: sparse_attention_op (block-sparse CSR attention). On TPU
    the MXU wants dense tiles; the CSR pattern is honored as a mask over a
    dense flash-style computation (XLA fuses the masked softmax), which is
    the TPU-idiomatic equivalent for the shapes this op targets."""
    def fn(q, k, v, offs, cols, *masks):
        b, h, s, d = q.shape
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
        nnz = cols.shape[-1]

        def one_mask(off_bh, col_bh):
            # CSR row of each nnz slot: searchsorted over the offset vector
            row = jnp.searchsorted(off_bh.astype(jnp.int32),
                                   jnp.arange(nnz), side="right") - 1
            return jnp.zeros((s, s), bool).at[row, col_bh].set(True)
        mask = jax.vmap(jax.vmap(one_mask))(offs, cols)      # [b, h, s, s]
        logits = jnp.where(mask, logits, -1e30)
        mi = 0
        if key_padding_mask is not None:
            kpm = masks[mi]; mi += 1                          # [b, s]
            logits = jnp.where(kpm[:, None, None, :] != 0, logits, -1e30)
        if attn_mask is not None:
            am = masks[mi]; mi += 1                           # [s, s]-ish
            logits = jnp.where(jnp.broadcast_to(am != 0, logits.shape),
                               logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    extra = [m for m in (key_padding_mask, attn_mask) if m is not None]
    return apply_op("sparse_attention", fn,
                    [query, key, value, sparse_csr_offset,
                     sparse_csr_columns] + extra)


# --------------------------------------------- 3-D pools, unpool, fold, convT

def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    fn, *_ = _pool(x, kernel_size, stride, padding, 3, lax.max,
                   lambda dt: -jnp.inf if jnp.issubdtype(dt, jnp.floating)
                   else jnp.iinfo(dt).min, data_format)
    out = apply_op("max_pool3d", fn, [x])
    if return_mask:
        raise NotImplementedError("return_mask: use max_pool2d for unpool")
    return out


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    k = _norm_tuple(kernel_size, 3)
    _, dims, strides, pads, _ = _pool(
        x, kernel_size, stride, padding, 3, lax.add,
        lambda dt: jnp.array(0, dt), data_format)

    def fn(a):
        ssum = lax.reduce_window(a, jnp.array(0, a.dtype), lax.add, dims,
                                 strides, pads)
        if divisor_override:
            return ssum / divisor_override
        if exclusive and not isinstance(pads, str):
            ones = jnp.ones(a.shape, a.dtype)
            cnt = lax.reduce_window(ones, jnp.array(0, a.dtype), lax.add,
                                    dims, strides, pads)
            return ssum / cnt
        return ssum / math.prod(k)
    return apply_op("avg_pool3d", fn, [x])


def _adaptive_pool(x, output_size, n, op_name, reduce_fn):
    outs = _norm_tuple(output_size, n)

    def fn(a):
        sp = a.shape[-n:]
        if all(s % o == 0 for s, o in zip(sp, outs)):
            shp = list(a.shape[:-n])
            red_axes = []
            for i, (s, o) in enumerate(zip(sp, outs)):
                shp.extend([o, s // o])
                red_axes.append(len(shp) - 1)
            return reduce_fn(a.reshape(shp), tuple(red_axes))
        # general bins (python loops over the static output size)
        def bins(s, o):
            return [(int(np.floor(i * s / o)), int(np.ceil((i + 1) * s / o)))
                    for i in builtins.range(o)]
        grids = [bins(s, o) for s, o in zip(sp, outs)]
        import itertools
        parts = jnp.stack([
            reduce_fn(a[(...,) + tuple(builtins.slice(b0, b1)
                                       for b0, b1 in combo)],
                      tuple(builtins.range(a.ndim - n, a.ndim)))
            for combo in itertools.product(*grids)], axis=-1)
        return parts.reshape(a.shape[:-n] + tuple(outs))
    return apply_op(op_name, fn, [x])


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, "adaptive_avg_pool3d",
                          lambda a, ax: a.mean(axis=ax))


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "adaptive_max_pool3d",
                          lambda a, ax: a.max(axis=ax))


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "adaptive_max_pool1d",
                          lambda a, ax: a.max(axis=ax))


def _max_pool_with_index(x, kernel, n):
    """Non-overlapping window max + flat argmax indices (the segnet-style
    pool/unpool pair; reference max_pool{2,3}d return_mask + max_unpool).
    Requires stride == kernel_size and divisible spatial dims — the case
    the reference unpool targets."""
    k = _norm_tuple(kernel, n)

    def fn(a):
        sp = a.shape[-n:]
        if any(s % kk for s, kk in zip(sp, k)):
            raise ValueError(
                f"max_unpool path needs spatial {sp} divisible by kernel {k}")
        lead = a.shape[:-n]
        # reshape into window blocks: [..., o1, k1, o2, k2, ...]
        shp = list(lead)
        for s, kk in zip(sp, k):
            shp.extend([s // kk, kk])
        blocks = a.reshape(shp)
        # move window dims last
        nd = len(shp)
        win_axes = [len(lead) + 2 * i + 1 for i in builtins.range(n)]
        out_axes = [len(lead) + 2 * i for i in builtins.range(n)]
        perm = list(builtins.range(len(lead))) + out_axes + win_axes
        blk = blocks.transpose(perm)
        flat_w = math.prod(k)
        blk2 = blk.reshape(blk.shape[:len(lead) + n] + (flat_w,))
        local = jnp.argmax(blk2, axis=-1)
        vals = jnp.max(blk2, axis=-1)
        # local window idx -> flat spatial idx of the input
        outs = [s // kk for s, kk in zip(sp, k)]
        local_coords = []
        rem = local
        for kk in reversed(k):
            local_coords.append(rem % kk)
            rem = rem // kk
        local_coords = local_coords[::-1]
        grids = jnp.meshgrid(*[jnp.arange(o) for o in outs], indexing="ij")
        flat = jnp.zeros_like(local)
        for i in builtins.range(n):
            coord = grids[i] * k[i] + local_coords[i]
            stride_i = math.prod(sp[i + 1:]) if i + 1 < n else 1
            flat = flat + coord * stride_i
        return vals, flat.astype(jnp.int32)
    return fn


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """reference: max_unpool2d — scatter pooled values back to their argmax
    positions (indices flat over H*W, as produced by max_pool2d
    return_mask)."""
    k = _norm_tuple(kernel_size, 2)
    s = _norm_tuple(stride if stride is not None else kernel_size, 2)

    def fn(a, idx):
        n, c, oh, ow = a.shape
        H = output_size[-2] if output_size else oh * s[0]
        W = output_size[-1] if output_size else ow * s[1]
        out = jnp.zeros((n, c, H * W), a.dtype)
        flat_idx = idx.reshape(n, c, -1)
        out = out.at[jnp.arange(n)[:, None, None],
                     jnp.arange(c)[None, :, None], flat_idx].set(
            a.reshape(n, c, -1))
        return out.reshape(n, c, H, W)
    return apply_op("max_unpool2d", fn, [x, indices])


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    st = k if stride is None else (stride if isinstance(stride, int) else stride[0])

    def fn(a, idx):
        n, c, ol = a.shape
        L = output_size[-1] if output_size else ol * st
        out = jnp.zeros((n, c, L), a.dtype)
        return out.at[jnp.arange(n)[:, None, None],
                      jnp.arange(c)[None, :, None], idx].set(a)
    return apply_op("max_unpool1d", fn, [x, indices])


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    k = _norm_tuple(kernel_size, 3)
    s = _norm_tuple(stride if stride is not None else kernel_size, 3)

    def fn(a, idx):
        n, c = a.shape[:2]
        osp = a.shape[2:]
        sp = (tuple(output_size[-3:]) if output_size
              else tuple(o * ss for o, ss in zip(osp, s)))
        out = jnp.zeros((n, c, math.prod(sp)), a.dtype)
        out = out.at[jnp.arange(n)[:, None, None],
                     jnp.arange(c)[None, :, None],
                     idx.reshape(n, c, -1)].set(a.reshape(n, c, -1))
        return out.reshape((n, c) + sp)
    return apply_op("max_unpool3d", fn, [x, indices])


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im (reference: functional/common.py fold) — inverse of unfold,
    overlaps sum."""
    out_hw = _norm_tuple(output_sizes, 2)
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    p = _norm_tuple(paddings, 2)
    d = _norm_tuple(dilations, 2)

    def fn(a):
        n, ckk, L = a.shape
        c = ckk // (k[0] * k[1])
        oh = (out_hw[0] + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (out_hw[1] + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        cols = a.reshape(n, c, k[0], k[1], oh, ow)
        H = out_hw[0] + 2 * p[0]
        W = out_hw[1] + 2 * p[1]
        out = jnp.zeros((n, c, H, W), a.dtype)
        for i in builtins.range(k[0]):
            for j in builtins.range(k[1]):
                hi = i * d[0]
                wj = j * d[1]
                out = out.at[:, :, hi: hi + oh * s[0]: s[0],
                             wj: wj + ow * s[1]: s[1]].add(cols[:, :, i, j])
        return out[:, :, p[0]: H - p[0], p[1]: W - p[1]]
    return apply_op("fold", fn, [x])


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    """1-D transposed conv via the 2-D path on a unit height axis."""
    from ..core.ops import squeeze, unsqueeze
    x4 = unsqueeze(x, 2)
    w4 = apply_op("unsq_w", lambda w: w[:, :, None, :], [weight])
    st = stride if isinstance(stride, int) else stride[0]
    pd = padding if isinstance(padding, (int, str)) else padding[0]
    op = output_padding if isinstance(output_padding, int) else output_padding[0]
    dl = dilation if isinstance(dilation, int) else dilation[0]
    out = conv2d_transpose(x4, w4, bias, stride=(1, st),
                           padding=(0, pd) if not isinstance(pd, str) else pd,
                           output_padding=(0, op), groups=groups,
                           dilation=(1, dl))
    return squeeze(out, 2)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    strides = _norm_tuple(stride, 3)
    dil = _norm_tuple(dilation, 3)
    pad = _norm_tuple(padding, 3) if not isinstance(padding, str) else padding
    out_pad = _norm_tuple(output_padding, 3)

    def fn(a, w, *b):
        ks = w.shape[2:]
        if isinstance(pad, str):
            padding_cfg = pad.upper()
        else:
            padding_cfg = [
                (dil[i] * (kk - 1) - pad[i],
                 dil[i] * (kk - 1) - pad[i] + out_pad[i])
                for i, kk in enumerate(ks)]
        dn = ("NCDHW", "OIDHW", "NCDHW")
        if groups == 1:
            out = lax.conv_transpose(a, w, strides=strides,
                                     padding=padding_cfg, rhs_dilation=dil,
                                     dimension_numbers=dn,
                                     transpose_kernel=True)
        else:
            xs = jnp.split(a, groups, axis=1)
            ws = jnp.split(w, groups, axis=0)
            out = jnp.concatenate(
                [lax.conv_transpose(xi, wi, strides=strides,
                                    padding=padding_cfg, rhs_dilation=dil,
                                    dimension_numbers=dn,
                                    transpose_kernel=True)
                 for xi, wi in zip(xs, ws)], axis=1)
        if b:
            out = out + b[0].reshape(1, -1, 1, 1, 1)
        return out
    args = [x, weight] + ([bias] if bias is not None else [])
    return apply_op("conv3d_transpose", fn, args)
