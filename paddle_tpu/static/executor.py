"""Static Executor — replays a recorded Program as one jitted XLA program.

TPU-native redesign of the reference's executor stack (SURVEY §3.2):
Executor.run → _ExecutorCache → StandaloneExecutor → InterpreterCore
(python/paddle/fluid/executor.py:921,1387,750; interpretercore.cc). The
reference builds instruction lists, a dependency graph, stream-event
insertion and an async workqueue to extract cross-op parallelism at run time;
under XLA all of that is the compiler's job — the whole program (forward,
backward via jax.value_and_grad, optimizer update) lowers to ONE fused HLO
module with buffer donation, and the "executor cache" is a dict keyed by
(program version, feed shapes, fetch list), mirroring _ExecutorCache
(executor.py:750) keyed on (program, feed, fetch).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core import random as random_mod
from ..core.tensor import Tensor, Parameter
from .program import Program, Variable, default_main_program


class _ScopeVar:
    def __init__(self, name, ref):
        self.name = name
        self._ref = ref

    def get_tensor(self):
        return self._ref


class Scope:
    """Name → persistent tensor map (reference: framework/scope.h:49; here
    parameters already live on-device as jax.Arrays inside Parameter objects,
    so the scope is a name index, not an owner)."""

    def __init__(self):
        self._vars: Dict[str, Tensor] = {}

    def find_var(self, name) -> Optional[_ScopeVar]:
        t = self._vars.get(name)
        return _ScopeVar(name, t) if t is not None else None

    def var_names(self):
        return list(self._vars)

    def _register(self, name, t):
        if name:
            self._vars[name] = t


_global_scope = Scope()


def _amp_replay_cast(node, args):
    """Re-apply the amp policy captured at record time (static AMP — the
    reference rewrites programs with cast ops via the AMP meta-optimizer;
    here the recorded policy casts at replay inside the jitted program)."""
    # note: `from ..amp import auto_cast` would grab the auto_cast FUNCTION
    # re-exported by the package, not the module
    from ..amp.auto_cast import amp_cast_inputs
    return amp_cast_inputs(node.name, args, st=node.amp_state)


def global_scope() -> Scope:
    return _global_scope


class CompiledProgram:
    """API-parity shim (reference: fluid/compiler.py CompiledProgram). All
    programs compile through XLA here, so this only tags fetch/build options."""

    def __init__(self, program_or_graph, build_strategy=None):
        self.program = program_or_graph
        self.build_strategy = build_strategy

    def with_data_parallel(self, *a, **kw):  # legacy PE path: XLA shards instead
        return self


class Executor:
    """reference: paddle.static.Executor (fluid/executor.py:921)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}
        self._opt_state: Dict[int, list] = {}
        self._step_i: Dict[int, int] = {}

    # ------------------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True,
            scope=None, **kw):
        if isinstance(program, CompiledProgram):
            program = program.program
        if hasattr(program, "_ps_serve"):
            # fluid DistributeTranspiler pserver program: block serving
            # (the reference's Listen&Serv loop) — see fluid/transpiler.py
            return program._ps_serve()
        prog: Program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []

        for p in prog._params:
            _global_scope._register(p.name, p)

        # startup programs / empty mains: initializers already ran eagerly
        if not prog._nodes:
            return []

        fetch_vids = tuple(self._fetch_vid(prog, f) for f in fetch_list)
        train = prog._optimizer is not None and prog._loss_vid is not None
        ps_bridge = getattr(prog, "_ps_dist", None) if train else None

        feed_arrays = []
        feed_sig = []
        for v in prog._feed_vars:
            if v.feed_name not in feed:
                raise KeyError(f"missing feed {v.feed_name!r}")
            arr = feed[v.feed_name]
            arr = arr._data if isinstance(arr, Tensor) else jnp.asarray(
                np.asarray(arr), dtype=v._data.dtype)
            feed_arrays.append(arr)
            feed_sig.append((tuple(arr.shape), str(arr.dtype)))

        diff_params = [p for p in prog._params if not p.stop_gradient
                       and np.issubdtype(np.dtype(p._data.dtype), np.floating)]
        _diff_ids = {id(p) for p in diff_params}
        const_params = [p for p in prog._params if id(p) not in _diff_ids]

        # cache key includes the trainable partition: freezing a parameter
        # between runs must trigger a rebuild, not bind wrong slots
        part_sig = tuple(id(p) in _diff_ids for p in prog._params)
        mode = "ps" if ps_bridge is not None else (
            "train" if train else "infer")
        key = (prog.id, prog._version, tuple(feed_sig), fetch_vids, mode,
               part_sig)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build(prog, fetch_vids, train,
                             ps_grads=ps_bridge is not None)
            self._cache[key] = fn
        keys = tuple(random_mod.split_key() for _ in prog._key_vars)

        if ps_bridge is not None:
            # PS-distributed fluid training: the step returns GRADS; the
            # bridge pushes them to the parameter servers (which apply the
            # update) and pulls fresh params back into the program
            fetches, grads = fn(tuple(p._data for p in diff_params),
                                tuple(p._data for p in const_params),
                                keys, *feed_arrays)
            ps_bridge.apply(diff_params,
                            [np.asarray(g, np.float32) for g in grads],
                            prog._optimizer.get_lr())
        elif train:
            opt = prog._optimizer
            if prog.id not in self._opt_state:
                self._opt_state[prog.id] = [opt.init_state(p._data) for p in diff_params]
            self._step_i[prog.id] = self._step_i.get(prog.id, 0) + 1
            fetches, new_params, new_state = fn(
                tuple(p._data for p in diff_params),
                tuple(p._data for p in const_params),
                tuple(self._opt_state[prog.id]),
                jnp.float32(opt.get_lr()), jnp.int32(self._step_i[prog.id]),
                keys, *feed_arrays)
            for p, na in zip(diff_params, new_params):
                p._data = na
                p._node = None
            self._opt_state[prog.id] = list(new_state)
        else:
            fetches = fn(tuple(p._data for p in diff_params),
                         tuple(p._data for p in const_params),
                         keys, *feed_arrays)

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # ------------------------------------------------------------------
    def _fetch_vid(self, prog, f):
        if isinstance(f, Variable):
            return f.vid
        if isinstance(f, str):
            return prog.global_block().var(f).vid
        raise TypeError(f"fetch_list entries must be Variable or name, got {type(f)}")

    def _build(self, prog, fetch_vids, train, feed_vars=None,
               ps_grads=False):
        # backward-slice the op list to the ancestors of what we actually
        # compute (the reference's Prune pass over ProgramDesc —
        # framework/prune.cc — done here as a reverse walk over the DAG)
        targets = set(fetch_vids)
        if train and prog._loss_vid is not None:
            targets.add(prog._loss_vid)
        for tvid, xvid, _ in prog._var_grads:
            targets.add(tvid)
            targets.add(xvid)  # grad point may be an intermediate; keep its producer
        needed = set(targets)
        kept = []
        for node in reversed(prog._nodes):
            if any(v in needed for v in node.out_vids):
                kept.append(node)
                for kind, ref in node.inputs:
                    if kind == "v":
                        needed.add(ref)
        nodes = list(reversed(kept))
        # the compiled fn accepts feeds positionally in this exact order;
        # callers (run / save_inference_model) pass the same list
        feed_list = list(feed_vars) if feed_vars is not None else list(prog._feed_vars)
        missing = needed - {v.vid for v in feed_list} - {
            vid for n in nodes for vid in n.out_vids} - {
            v.vid for v in prog._key_vars} - set(
            prog._grad_of.values()) - {g for _, _, g in prog._var_grads}
        if missing:
            names = [prog._vars[m].name for m in sorted(missing) if m in prog._vars]
            raise KeyError(f"program needs feeds not provided: {names}")
        feed_vids = [v.vid for v in feed_list]
        key_vids = [v.vid for v in prog._key_vars]
        diff_params = [p for p in prog._params if not p.stop_gradient
                       and np.issubdtype(np.dtype(p._data.dtype), np.floating)]
        diff_idx = {id(p): i for i, p in enumerate(diff_params)}
        const_params = [p for p in prog._params if id(p) not in diff_idx]
        const_idx = {id(p): i for i, p in enumerate(const_params)}
        param_slot = []  # program param index -> ("d"/"k", position)
        for p in prog._params:
            if id(p) in diff_idx:
                param_slot.append(("d", diff_idx[id(p)]))
            else:
                param_slot.append(("k", const_idx[id(p)]))
        loss_vid = prog._loss_vid
        grad_of = dict(prog._grad_of)   # program param index -> grad vid
        var_grads = list(prog._var_grads)
        opt = prog._optimizer
        wds = [opt._wd_for(p) for p in diff_params] if opt is not None else None
        grad_clip = getattr(opt, "_grad_clip", None) if opt is not None else None

        def replay(dpa, kpa, keys, feeds, var_override=None):
            # var_override: {vid: array} — value substituted for that
            # variable wherever it would be bound (feed or op output); used
            # to differentiate a target wrt an arbitrary graph variable
            env = {}
            var_override = var_override or {}
            for vid, a in zip(feed_vids, feeds):
                env[vid] = var_override.get(vid, a)
            for vid, k in zip(key_vids, keys):
                env[vid] = k
            for node in nodes:
                args = []
                for kind, ref in node.inputs:
                    if kind == "v":
                        args.append(env[ref])
                    elif kind == "p":
                        tag, pos = param_slot[ref]
                        args.append(dpa[pos] if tag == "d" else kpa[pos])
                    else:
                        args.append(ref)
                if node.amp_state is not None:
                    args = _amp_replay_cast(node, args)
                out = node.fn(*args, **node.kwargs)
                if node.multi:
                    for ov, o in zip(node.out_vids, out):
                        env[ov] = var_override.get(ov, o)
                else:
                    ov = node.out_vids[0]
                    env[ov] = var_override.get(ov, out)
            return env

        def eval_var_grads(env, dpa, kpa, keys, feeds):
            # static.gradients() outputs: d(sum target)/d(var), computed by
            # re-replaying with the variable's value as the point of
            # differentiation (works for feeds and intermediates alike)
            for tvid, xvid, gvid in var_grads:
                def tgt(xa, _x=xvid, _t=tvid):
                    env2 = replay(dpa, kpa, keys, feeds, var_override={_x: xa})
                    return jnp.sum(env2[_t].astype(jnp.float32))
                env[gvid] = jax.grad(tgt)(env[xvid])

        if ps_grads:
            # DistributeTranspiler trainer step: loss + grads only; the
            # optimizer applies SERVER-side (fluid/transpiler.py)
            def ps_step(dpa, kpa, keys, *feeds):
                def loss_fn(pa):
                    env = replay(pa, kpa, keys, feeds)
                    return env[loss_vid].astype(jnp.float32), env
                (_, env), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(list(dpa))
                for pidx, gvid in grad_of.items():
                    tag, pos = param_slot[pidx]
                    if tag == "d":
                        env[gvid] = grads[pos]
                eval_var_grads(env, dpa, kpa, keys, feeds)
                fetches = tuple(env[v] for v in fetch_vids)
                return fetches, tuple(grads)

            return jax.jit(ps_step)

        if train:
            def step(dpa, kpa, opt_state, lr, step_i, keys, *feeds):
                def loss_fn(pa):
                    env = replay(pa, kpa, keys, feeds)
                    return env[loss_vid].astype(jnp.float32), env
                (_, env), grads = jax.value_and_grad(loss_fn, has_aux=True)(list(dpa))
                for pidx, gvid in grad_of.items():
                    tag, pos = param_slot[pidx]
                    if tag == "d":
                        env[gvid] = grads[pos]
                if grad_clip is not None and type(grad_clip).__name__ == "ClipGradByGlobalNorm":
                    total = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                                         for g in grads))
                    scale = jnp.minimum(1.0, grad_clip.clip_norm / jnp.maximum(total, 1e-12))
                    grads = [g * scale.astype(g.dtype) for g in grads]
                new_params, new_state = [], []
                for pa, g, st, wd in zip(dpa, grads, opt_state, wds):
                    np_, ns_ = opt.update(pa, g, st, lr, step_i, wd)
                    new_params.append(np_)
                    new_state.append(ns_)
                eval_var_grads(env, dpa, kpa, keys, feeds)
                fetches = tuple(env[v] for v in fetch_vids)
                return fetches, tuple(new_params), tuple(new_state)

            return jax.jit(step, donate_argnums=(0, 2))

        def run_fn(dpa, kpa, keys, *feeds):
            env = replay(dpa, kpa, keys, feeds)
            eval_var_grads(env, dpa, kpa, keys, feeds)
            return tuple(env[v] for v in fetch_vids)

        return jax.jit(run_fn)
