"""sequence_* op family — the reference's LoD sequence corpus, TPU-native.

Reference (SURVEY §2 op corpus; VERDICT r1 missing #4):
paddle/fluid/operators/sequence_ops/ (30+ kernels) surfaced as
python/paddle/static/nn/sequence_lod.py — all built on LoD (ragged)
tensors, a representation XLA does not have. The TPU-native contract is the
**padded-dense + lengths** pair the rest of this framework already uses
(F.sequence_mask, CTC, RNN packing):

  * "a batch of sequences" = `x: [B, T, ...]` padded dense + `lengths: [B]`
  * functions that change per-row lengths return `(out, new_lengths)`
  * reductions/elementwise keep shapes static so everything jits; the only
    host-dependent op is sequence_unpad (flat total is data-dependent).

Every function documents the reference analog it covers.
"""
from __future__ import annotations

import builtins

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..nn import functional as F


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _mask(lengths, T, ndim_extra=0):
    m = jnp.arange(T)[None, :] < _arr(lengths)[:, None]
    return m.reshape(m.shape + (1,) * ndim_extra)


def _lengths_or_full(x, lengths):
    """lengths=None means "every row is full length" (the dense-tensor
    degenerate case of a LoD batch)."""
    if lengths is not None:
        return lengths
    a = _arr(x)
    return jnp.full((a.shape[0],), a.shape[1], jnp.int64)


# -------------------------------------------------------------- reductions
def sequence_pool(input, pool_type, lengths=None, is_test=False,  # noqa: A002
                  pad_value=0.0, name=None):
    """reference: sequence_lod.py:253 (sum/average/sqrt/max/last/first).
    input [B, T, H], lengths [B] -> [B, H]; empty rows get pad_value."""
    pt = pool_type.lower()

    def fn(x, ln):
        B, T = x.shape[0], x.shape[1]
        m = _mask(ln, T, x.ndim - 2)
        xm = jnp.where(m, x, 0.0)
        ln_f = jnp.maximum(ln, 1).astype(x.dtype).reshape(
            (B,) + (1,) * (x.ndim - 2))
        if pt == "sum":
            out = xm.sum(1)
        elif pt == "average":
            out = xm.sum(1) / ln_f
        elif pt == "sqrt":
            out = xm.sum(1) / jnp.sqrt(ln_f)
        elif pt == "max":
            neg = jnp.asarray(jnp.finfo(
                x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                else jnp.float32).min, x.dtype)
            out = jnp.where(m, x, neg).max(1)
        elif pt == "first":
            out = x[:, 0]
        elif pt == "last":
            idx = jnp.maximum(ln - 1, 0)
            out = jnp.take_along_axis(
                x, idx.reshape((B, 1) + (1,) * (x.ndim - 2)), axis=1)[:, 0]
        else:
            raise ValueError(f"unknown pool_type {pool_type!r}")
        empty = (ln == 0).reshape((B,) + (1,) * (x.ndim - 2))
        return jnp.where(empty, jnp.asarray(pad_value, out.dtype), out)

    return apply_op("sequence_pool", fn, [input, _lengths_or_full(input, lengths)])


def sequence_first_step(input, lengths=None, name=None):  # noqa: A002
    """reference: sequence_lod.py:441."""
    return sequence_pool(input, "first", lengths)


def sequence_last_step(input, lengths=None, name=None):  # noqa: A002
    """reference: sequence_lod.py:499."""
    return sequence_pool(input, "last", lengths)


def sequence_softmax(input, lengths=None, use_cudnn=False, name=None):  # noqa: A002
    """reference: sequence_lod.py:166 — softmax over each row's valid
    prefix; padding gets 0."""
    def fn(x, ln):
        T = x.shape[1]
        m = _mask(ln, T, x.ndim - 2)
        neg = jnp.asarray(-1e30, jnp.float32)
        z = jnp.where(m, x.astype(jnp.float32), neg)
        p = jax.nn.softmax(z, axis=1)
        return jnp.where(m, p, 0.0).astype(x.dtype)
    return apply_op("sequence_softmax", fn, [input, _lengths_or_full(input, lengths)])


# ----------------------------------------------------------- restructuring
def sequence_concat(input, lengths, name=None):  # noqa: A002
    """reference: sequence_lod.py:371 — per-row concatenation of N
    sequence batches: row b of the output is xs[0][b][:l0] ++ xs[1][b][:l1]
    ++ .... Returns (out [B, sum(T_i), ...], new_lengths)."""
    def fn(*args):
        n = len(args) // 2
        xs, lns = args[:n], args[n:]
        B = xs[0].shape[0]
        T_out = builtins.sum(x.shape[1] for x in xs)
        feat = xs[0].shape[2:]
        out = jnp.zeros((B, T_out) + feat, xs[0].dtype)
        total = jnp.zeros((B,), lns[0].dtype)
        for x, ln in zip(xs, lns):
            T = x.shape[1]
            # scatter x's valid prefix at per-row offset `total`
            tpos = jnp.arange(T)[None, :]
            dst = total[:, None] + tpos                       # [B, T]
            valid = tpos < ln[:, None]
            dst = jnp.where(valid, dst, T_out)                # sentinel slot
            bidx = jnp.broadcast_to(jnp.arange(B)[:, None], dst.shape)
            out = jnp.pad(out, [(0, 0), (0, 1)] + [(0, 0)] * len(feat)) \
                .at[bidx, dst].set(x)[:, :T_out]
            total = total + ln
        return out, total
    args = list(input) + list(lengths)
    return apply_op("sequence_concat", fn, args, n_outputs=2)


def sequence_slice(input, offset, length, lengths=None, name=None):  # noqa: A002
    """reference: sequence_lod.py:558 — per-row [offset : offset+length)
    window. Returns (out [B, max_len, ...], length)."""
    def fn(x, off, ln):
        B, T = x.shape[0], x.shape[1]
        off = off.reshape(B)
        ln2 = ln.reshape(B)
        Tmax = int(x.shape[1])
        tpos = jnp.arange(Tmax)[None, :]
        src = jnp.clip(off[:, None] + tpos, 0, T - 1)
        out = jnp.take_along_axis(
            x, src.reshape((B, Tmax) + (1,) * (x.ndim - 2)), axis=1)
        m = (tpos < ln2[:, None]).reshape((B, Tmax) + (1,) * (x.ndim - 2))
        return jnp.where(m, out, 0.0), ln2
    return apply_op("sequence_slice", fn, [input, offset, length],
                    n_outputs=2)


def sequence_reverse(x, lengths=None, name=None):
    """reference: sequence_lod.py:1414 — reverse each row's valid prefix,
    padding stays in place."""
    def fn(a, ln):
        B, T = a.shape[0], a.shape[1]
        tpos = jnp.arange(T)[None, :]
        src = ln[:, None] - 1 - tpos
        src = jnp.where(src >= 0, src, tpos)   # padding: identity
        return jnp.take_along_axis(
            a, src.reshape((B, T) + (1,) * (a.ndim - 2)), axis=1)
    return apply_op("sequence_reverse", fn, [x, _lengths_or_full(x, lengths)])


def sequence_pad(x, pad_value, lengths, maxlen=None, name=None):
    """reference: sequence_lod.py:911 — here the ragged input is already
    (padded buffer, lengths); this repads to `maxlen` with pad_value and
    returns (out, lengths) like the reference's (Out, Length)."""
    lv = _arr(lengths)
    if maxlen is not None and not isinstance(lv, jax.core.Tracer):
        top = int(np.asarray(jnp.max(lv)))
        if top > int(maxlen):
            # reference: sequence_pad enforces maxlen >= every sequence
            raise ValueError(
                f"sequence_pad: maxlen={maxlen} < longest sequence {top}")

    def fn(a, pv, ln):
        T = a.shape[1]
        m = _mask(ln, T, a.ndim - 2)
        out = jnp.where(m, a, pv.astype(a.dtype))
        if maxlen is not None and int(maxlen) != T:
            M = int(maxlen)
            if M > T:
                pads = [(0, 0), (0, M - T)] + [(0, 0)] * (a.ndim - 2)
                out = jnp.pad(out, pads, constant_values=0)
                out = jnp.where(_mask(ln, M, a.ndim - 2), out,
                                pv.astype(a.dtype))
            else:
                out = out[:, :M]
            ln = jnp.minimum(ln, M)
        return out, ln
    return apply_op("sequence_pad", fn, [x, pad_value, lengths], n_outputs=2)


def sequence_unpad(x, length, name=None):
    """reference: sequence_lod.py:1032 — drop padding, concatenate valid
    rows: [B, T, ...] + lengths -> [sum(lengths), ...]. Output size is
    data-dependent: eager host op (like masked_select)."""
    a = np.asarray(_arr(x))
    ln = np.asarray(_arr(length)).reshape(-1)
    rows = [a[b, :int(ln[b])] for b in builtins.range(a.shape[0])]
    return Tensor(jnp.asarray(np.concatenate(rows, axis=0)))


def sequence_reshape(input, new_dim, lengths=None, name=None):  # noqa: A002
    """reference: sequence_lod.py:1116 — re-chunk each row's valid payload
    into rows of width new_dim. Returns (out, new_lengths)."""
    lv = _arr(_lengths_or_full(input, lengths))
    H0 = int(_arr(input).shape[-1])
    if (int(_arr(input).shape[1]) * H0) % new_dim:
        raise ValueError(
            f"sequence_reshape: new_dim={new_dim} must divide the padded "
            f"row payload T*H={int(_arr(input).shape[1]) * H0}")
    if not isinstance(lv, jax.core.Tracer):
        bad = np.asarray((lv * H0) % new_dim)
        if (bad != 0).any():
            # reference LoD op requires per-sequence divisibility
            raise ValueError(
                f"sequence_reshape: each row payload len*H must divide "
                f"new_dim={new_dim}; offending rows "
                f"{np.nonzero(bad)[0].tolist()}")

    def fn(x, ln):
        B, T, H = x.shape
        out = x.reshape(B, (T * H) // new_dim, new_dim)
        new_ln = (ln * H) // new_dim
        return out, new_ln
    return apply_op("sequence_reshape", fn,
                    [input, _lengths_or_full(input, lengths)], n_outputs=2)


def sequence_expand(x, y_lengths, x_lengths=None, ref_level=-1,
                    max_repeat=None, name=None):
    """reference: sequence_lod.py:652 — repeat row b of x y_lengths[b]
    times along a new ragged batch. Output [B, R, ...] padded over the
    repeat dim where R = max(y_lengths) (static-width form of the LoD
    expand). Under jit the repeat width must be static: pass max_repeat."""
    if max_repeat is None:
        yv = _arr(y_lengths)
        if isinstance(yv, jax.core.Tracer):
            raise ValueError(
                "sequence_expand under jit needs static max_repeat= (the "
                "output width max(y_lengths) cannot be data-dependent)")
        max_repeat = int(np.asarray(jnp.max(yv)))
    R = int(max_repeat)

    def fn(a, yln):
        B = a.shape[0]
        rep = jnp.arange(R)[None, :] < yln[:, None]
        out = jnp.broadcast_to(a[:, None], (B, R) + a.shape[1:])
        m = rep.reshape((B, R) + (1,) * (a.ndim - 1))
        return jnp.where(m, out, 0.0)
    return apply_op("sequence_expand", fn, [x, y_lengths])


def sequence_expand_as(x, y, y_lengths, name=None):
    """reference: sequence_lod.py:791 — expand each x row across y's row
    width: x [B, H] -> [B, T_y, H] masked by y_lengths."""
    def fn(a, yv, yln):
        T = yv.shape[1]
        out = jnp.broadcast_to(a[:, None], (a.shape[0], T) + a.shape[1:])
        m = _mask(yln, T, a.ndim - 1)
        return jnp.where(m, out, 0.0)
    return apply_op("sequence_expand_as", fn,
                    [x, y, _lengths_or_full(y, y_lengths)])


def sequence_scatter(input, index, updates, lengths=None, name=None):  # noqa: A002
    """reference: sequence_lod.py:1185 — per-row scatter-add of `updates`
    into `input` at per-row positions `index` (padding rows of index are
    masked by lengths)."""
    def fn(x, idx, upd, ln):
        B, T = idx.shape[0], idx.shape[1]
        valid = _mask(ln, T)
        safe = jnp.where(valid, idx, x.shape[1])
        bidx = jnp.broadcast_to(jnp.arange(B)[:, None], safe.shape)
        padded = jnp.pad(x, [(0, 0), (0, 1)])
        out = padded.at[bidx, safe].add(jnp.where(valid, upd, 0.0))
        return out[:, :x.shape[1]]
    return apply_op("sequence_scatter", fn,
                    [input, index, updates, _lengths_or_full(index, lengths)])


def sequence_enumerate(input, win_size, lengths=None, pad_value=0, name=None):  # noqa: A002
    """reference: sequence_lod.py:1281 — sliding windows of ids:
    [B, T] -> [B, T, win_size], positions past a row's length padded."""
    def fn(ids, ln):
        B, T = ids.shape
        tpos = jnp.arange(T)[:, None] + jnp.arange(win_size)[None, :]  # [T,W]
        src = jnp.clip(tpos, 0, T - 1)
        win = ids[:, src]                                   # [B, T, W]
        ok = (tpos[None] < ln[:, None, None])
        return jnp.where(ok, win, pad_value)
    return apply_op("sequence_enumerate", fn, [input, _lengths_or_full(input, lengths)])


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,  # noqa: A002
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, lengths=None, name=None,
                  weight=None, bias=None):
    """reference: sequence_lod.py:26 — context-window projection over time:
    each step's context [t+start, t+start+filter_size) (zero past row
    bounds) flattens into one matmul against [filter_size*H, num_filters].
    Pass `weight`/`bias` explicitly (functional form) or let it create them
    eagerly like static.nn.fc."""
    H = int(_arr(input).shape[-1])
    if weight is None:
        from .. import nn as dyn_nn
        lin = dyn_nn.Linear(filter_size * H, num_filters,
                            bias_attr=bias_attr if bias_attr is not None
                            else None)
        weight, bias = lin.weight, lin.bias
    start = padding_start if padding_start is not None \
        else -((filter_size - 1) // 2)

    def fn(x, ln, w, *b):
        B, T = x.shape[0], x.shape[1]
        tpos = jnp.arange(T)[:, None] + start + jnp.arange(filter_size)[None]
        src = jnp.clip(tpos, 0, T - 1)                      # [T, F]
        ctx = x[:, src]                                     # [B, T, F, H]
        ok = ((tpos >= 0)[None] & (tpos[None] < ln[:, None, None]))
        ctx = jnp.where(ok[..., None], ctx, 0.0)
        flat = ctx.reshape(B, T, filter_size * x.shape[-1])
        out = flat @ w
        if b:
            out = out + b[0]
        valid = _mask(ln, T, 1)
        out = jnp.where(valid, out, 0.0)
        return out[:, ::filter_stride] if filter_stride != 1 else out

    args = [input, _lengths_or_full(input, lengths), weight] \
        + ([bias] if bias is not None else [])
    out = apply_op("sequence_conv", fn, args)
    if act:
        out = getattr(F, act)(out)
    return out
