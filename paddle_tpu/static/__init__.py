"""paddle.static analog — static graph capability, TPU-native.

Reference surface: python/paddle/static/ (SURVEY §2.3: Executor,
CompiledProgram, Program/program_guard, data, append_backward/gradients,
save/load_inference_model, static nn layers). Design per SURVEY §7: the
Program is a recorded op-DAG replayed as ONE jitted XLA computation — the
InterpreterCore/instruction machinery of the reference
(new_executor/interpretercore.cc) is replaced by the XLA scheduler.
"""
from .program import (  # noqa: F401
    Program, Variable, program_guard, default_main_program,
    default_startup_program, data, append_backward, gradients,
    in_static_mode, enable_static, disable_static,
)
from .executor import Executor, CompiledProgram, Scope, global_scope  # noqa: F401
from .io import (  # noqa: F401
    save_inference_model, load_inference_model, save, load, normalize_program,
)
from . import nn  # noqa: F401

InputSpec = None  # populated lazily below to avoid import cycle


def _late_imports():
    global InputSpec
    from ..jit.api import InputSpec as _I
    InputSpec = _I


try:
    _late_imports()
except Exception:
    pass


class BuildStrategy:
    """Compat shim (reference: fluid/compiler.py BuildStrategy): every knob it
    exposes (fusion, memory reuse, reduce strategy) is an XLA decision here."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1


def name_scope(prefix=None):
    import contextlib
    return contextlib.nullcontext()


# ---------------------------------------------------------------------------
# Surface completion (reference python/paddle/static/__init__.py parity).

def cpu_places(device_count=None):
    import jax
    devs = [d for d in jax.devices() if d.platform == "cpu"] or jax.devices()
    n = device_count or len(devs)
    return (devs * n)[:n]


def cuda_places(device_ids=None):
    import jax
    devs = jax.devices()
    if device_ids is None:
        return list(devs)
    return [devs[i] for i in device_ids]


def npu_places(device_ids=None):
    return cuda_places(device_ids)


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def mlu_places(device_ids=None):
    return cuda_places(device_ids)


def device_guard(device=None):
    """reference: static.device_guard — op placement hint. XLA owns
    placement in the compiled program; scope kept for API compat."""
    import contextlib
    return contextlib.nullcontext()


import contextlib as _contextlib

_scope_stack = []


@_contextlib.contextmanager
def scope_guard(scope):
    """reference: static.scope_guard — swap the active variable Scope."""
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from .program import default_main_program
    import numpy as _np
    import paddle_tpu as _p
    var = _p.full(shape, value, dtype=dtype)
    var.persistable = persistable
    if name:
        var.name = name
    return var


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    import paddle_tpu as _p
    return _p.create_parameter(shape, dtype, name=name, attr=attr,
                               is_bias=is_bias,
                               default_initializer=default_initializer)


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,  # noqa: N802
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """reference: static.Print op — debug print that passes data through.
    Uses jax.debug.print so it also fires inside compiled programs."""
    import jax
    from ..core.tensor import Tensor, apply_op

    def fn(a):
        jax.debug.print((message or "") + " {}", a)
        return a
    return apply_op("print", fn, [input])


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: py_func op — host-python op in the graph. Eager/recorded
    execution calls it directly (jax.pure_callback under jit)."""
    import jax
    import numpy as _np
    from ..core.tensor import Tensor, apply_op
    xs = x if isinstance(x, (list, tuple)) else [x]

    def fn(*arrs):
        res = func(*[Tensor(a) for a in arrs])
        rs = res if isinstance(res, (list, tuple)) else [res]
        return tuple(r._data if isinstance(r, Tensor) else jax.numpy.asarray(r)
                     for r in rs)
    n_out = len(out) if isinstance(out, (list, tuple)) else 1
    result = apply_op("py_func", fn, list(xs), n_outputs=n_out if n_out > 1 else None)
    return result


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    """reference: static.accuracy — top-k accuracy."""
    import paddle_tpu as _p
    from ..core.tensor import apply_op
    import jax.numpy as jnp

    def fn(x, y):
        topk = jnp.argsort(x, axis=-1)[:, ::-1][:, :k]
        hit = (topk == y.reshape(-1, 1)).any(axis=1)
        return hit.mean(dtype=jnp.float32)
    return apply_op("accuracy", fn, [input, label])


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,  # noqa: A002
        slide_steps=1, ins_tag_weight=None):
    """reference: static.auc — streaming AUC; here computed directly."""
    from ..metric import Auc
    import numpy as _np
    m = Auc(num_thresholds=num_thresholds)
    m.update(_np.asarray(input._data), _np.asarray(label._data))
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    return Tensor(jnp.asarray(_np.float32(m.accumulate())))


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """reference: fluid layers exponential_decay — lr * decay_rate^(t/N).
    The per-scheduler-step gamma is decay_rate^(1/decay_steps) so the rate
    drops by decay_rate exactly every decay_steps steps (the smooth,
    non-staircase form)."""
    from ..optimizer.lr import ExponentialDecay
    return ExponentialDecay(gamma=float(decay_rate) ** (1.0 / decay_steps),
                            learning_rate=learning_rate)


class ExponentialMovingAverage:
    """reference: static ExponentialMovingAverage — shadow variables with
    bias-corrected decay; apply()/restore() swap them in and out."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._step = 0
        self._shadow = {}
        self._backup = {}
        self._params = []

    def _collect(self, program=None):
        if not self._params:
            from ..static.program import default_main_program
        return self._params

    def register(self, params):
        """Track a list of Parameters (dynamic-friendly entry point)."""
        import numpy as _np
        self._params = list(params)
        for p in self._params:
            self._shadow[id(p)] = _np.asarray(p._data, _np.float32).copy()

    def update(self):
        import numpy as _np
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in self._params:
            s = self._shadow[id(p)]
            self._shadow[id(p)] = d * s + (1 - d) * _np.asarray(
                p._data, _np.float32)

    def apply(self, executor=None, need_restore=True):
        import contextlib
        import jax.numpy as jnp

        @contextlib.contextmanager
        def _ctx():
            import numpy as _np
            for p in self._params:
                self._backup[id(p)] = p._data
                p._data = jnp.asarray(self._shadow[id(p)].astype(
                    _np.asarray(p._data).dtype))
            try:
                yield
            finally:
                if need_restore:
                    for p in self._params:
                        p._data = self._backup.pop(id(p))
        return _ctx()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup.pop(id(p))


class WeightNormParamAttr:
    """reference: static WeightNormParamAttr — weight-norm
    reparameterization attr; maps to nn.utils.weight_norm here."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.trainable = trainable


ParallelExecutor = CompiledProgram  # reference alias: multi-device executor


class IpuStrategy:
    """reference: IPU backend config — not a supported device here."""

    def __init__(self, *a, **k):
        raise RuntimeError("IPU backend is not available in paddle_tpu "
                           "(TPU-native build; reference gates this behind "
                           "WITH_IPU)")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise RuntimeError("IPU backend is not available in paddle_tpu")


def ipu_shard_guard(*a, **k):
    raise RuntimeError("IPU backend is not available in paddle_tpu")


def set_ipu_shard(*a, **k):
    raise RuntimeError("IPU backend is not available in paddle_tpu")


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    """reference: static.serialize_program — program bytes for deploy."""
    import pickle
    from .program import default_main_program
    prog = program or default_main_program()
    return pickle.dumps({"kind": "paddle_tpu_program",
                         "ops": getattr(prog, "_op_names", lambda: [])()
                         if callable(getattr(prog, "_op_names", None))
                         else None})


def deserialize_program(data):
    import pickle
    blob = pickle.loads(data)
    if not isinstance(blob, dict) or blob.get("kind") != "paddle_tpu_program":
        raise ValueError("not a serialized paddle_tpu program")
    from .program import Program
    return Program()


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    import pickle
    from .program import default_main_program
    prog = program or default_main_program()
    state = {}
    for name, var in getattr(prog, "_vars", {}).items():
        arr = getattr(var, "_data", None)
        if arr is not None and getattr(var, "persistable", False):
            import numpy as _np
            state[name] = _np.asarray(arr)
    return pickle.dumps(state)


def deserialize_persistables(program, data, executor=None):
    import pickle
    state = pickle.loads(data)
    for name, arr in state.items():
        var = getattr(program, "_vars", {}).get(name)
        if var is not None:
            import jax.numpy as jnp
            var._data = jnp.asarray(arr)
    return state


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def load_program_state(model_path, var_list=None):
    """reference: static.load_program_state — state dict from a saved
    model prefix (static.save writes <prefix>.pdparams via framework.io)."""
    from ..framework.io import load as _load
    import os as _os
    for suffix in (".pdparams", ""):
        p = model_path + suffix
        if _os.path.exists(p):
            return _load(p)
    raise FileNotFoundError(model_path)


def set_program_state(program, state):
    import jax.numpy as jnp
    for name, arr in state.items():
        var = getattr(program, "_vars", {}).get(name)
        if var is not None:
            var._data = jnp.asarray(arr)


def ctr_metric_bundle(input, label, ins_tag_weight=None):  # noqa: A002
    """reference: static.ctr_metric_bundle — (auc, batch_auc, stat tuple).
    Returns the directly-computed equivalents."""
    a = auc(input, label)
    return a, a
