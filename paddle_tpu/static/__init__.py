"""paddle.static analog — static graph capability, TPU-native.

Reference surface: python/paddle/static/ (SURVEY §2.3: Executor,
CompiledProgram, Program/program_guard, data, append_backward/gradients,
save/load_inference_model, static nn layers). Design per SURVEY §7: the
Program is a recorded op-DAG replayed as ONE jitted XLA computation — the
InterpreterCore/instruction machinery of the reference
(new_executor/interpretercore.cc) is replaced by the XLA scheduler.
"""
from .program import (  # noqa: F401
    Program, Variable, program_guard, default_main_program,
    default_startup_program, data, append_backward, gradients,
    in_static_mode, enable_static, disable_static,
)
from .executor import Executor, CompiledProgram, Scope, global_scope  # noqa: F401
from .io import (  # noqa: F401
    save_inference_model, load_inference_model, save, load, normalize_program,
)
from . import nn  # noqa: F401

InputSpec = None  # populated lazily below to avoid import cycle


def _late_imports():
    global InputSpec
    from ..jit.api import InputSpec as _I
    InputSpec = _I


try:
    _late_imports()
except Exception:
    pass


class BuildStrategy:
    """Compat shim (reference: fluid/compiler.py BuildStrategy): every knob it
    exposes (fusion, memory reuse, reduce strategy) is an XLA decision here."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1


def name_scope(prefix=None):
    import contextlib
    return contextlib.nullcontext()
