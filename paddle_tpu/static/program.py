"""Static graph Program — lazy op recording compiled to one XLA computation.

TPU-native redesign of the reference's static core (SURVEY §1 L2b): the
reference represents programs as ProgramDesc protobuf (framework.proto,
program_desc.h) interpreted op-by-op by InterpreterCore
(new_executor/interpretercore.cc). Here a Program is a recorded op-DAG over
symbolic `Variable`s that the Executor replays *inside one jax.jit* — the
"Program" **is** the jaxpr/HLO (SURVEY §7 design mapping: "InterpreterCore /
static Program → XLA computation; executor = compiled executable").

Recording happens at the single eager dispatch gate (`core.tensor.apply_op`):
when static mode is on and any op input is a `Variable`, the op is appended
to the current Program instead of executing, with output shapes/dtypes
derived by `jax.eval_shape` (the analog of the reference's infermeta/
functions, which exist precisely to share shape inference between static and
dynamic modes — here jax abstract eval is that shared path for free).

Concrete tensors created during build (parameter initializers, constants)
stay eager: the reference runs those in a separate "startup program"
(fluid/framework.py default_startup_program); here eager init IS the startup
program, so `exe.run(startup_program)` is a no-op kept for API parity.
"""
from __future__ import annotations

import contextlib
import itertools
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core import tensor as tensor_mod
from ..core import random as random_mod
from ..core.tensor import Tensor, Parameter
from ..core.dtype import convert_dtype


class Variable(Tensor):
    """Symbolic graph variable (reference: fluid/framework.py Variable over a
    VarDesc). `_data` holds a jax.ShapeDtypeStruct — shape/dtype introspection
    works at build time; host reads (`numpy()`, `item()`) do not, exactly as
    in the reference's static mode."""

    __slots__ = ("vid", "is_feed", "feed_name", "declared_shape", "is_key",
                 "program")

    def __init__(self, aval, name=None, vid=None):
        # bypass Tensor.__init__'s jnp.asarray: store the aval directly
        self._data = aval
        self.stop_gradient = True
        self.grad = None
        self._node = None
        self._out_idx = 0
        self.name = name
        self.persistable = False
        self._hooks = []
        self.pspec = None
        self.vid = vid
        self.is_feed = False
        self.feed_name = None
        self.declared_shape = None
        self.is_key = False
        self.program = None  # owning Program (reference: Variable.block.program)

    def numpy(self):
        raise RuntimeError(
            "Variable has no data at graph-build time; run it through "
            "paddle.static.Executor (reference static-mode semantics).")

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={list(self._data.shape)}, "
                f"dtype={self._data.dtype})")


class OpNode:
    """One recorded op: replayed as `fn(*inputs, **kwargs)` at run time.

    Inputs are tagged: ("v", vid) graph edge / ("c", array) build-time
    constant / ("p", index-into-program.params) parameter reference —
    the analog of OpDesc input names resolved against Scope variables
    (operator.h:154 Run(scope, place))."""

    __slots__ = ("name", "fn", "kwargs", "inputs", "out_vids", "multi",
                 "amp_state")

    def __init__(self, name, fn, kwargs, inputs, out_vids, multi,
                 amp_state=None):
        self.name = name
        self.fn = fn
        self.kwargs = kwargs
        self.inputs = inputs
        self.out_vids = out_vids
        self.multi = multi
        # amp policy active when this op was recorded (paddle.amp.auto_cast
        # around graph-building code, the static analog of the reference's
        # AMP meta-optimizer op-rewriting pass); the Executor re-applies the
        # cast at replay
        self.amp_state = amp_state


class Block:
    """Facade over the program's single global block (reference BlockDesc;
    nested control-flow blocks are unnecessary here — lax.cond/while close
    over values, so sub-blocks never materialize)."""

    def __init__(self, program):
        self.program = program

    @property
    def ops(self):
        return self.program._nodes

    @property
    def vars(self):
        return {v.name: v for v in self.program._vars.values() if v.name}

    def var(self, name):
        for v in self.program._vars.values():
            if v.name == name:
                return v
        raise ValueError(f"no variable named {name!r} in block")


_GLOBAL_VID = itertools.count()  # vids unique across ALL programs so
# cross-program references (control-flow capture probes) are unambiguous


class Program:
    """Recorded op-DAG (reference: fluid/framework.py Program / ProgramDesc)."""

    _ids = itertools.count()

    def __init__(self):
        self._nodes: List[OpNode] = []
        self._vars: Dict[int, Variable] = {}
        self._feed_vars: List[Variable] = []
        self._key_vars: List[Variable] = []
        self._params: List[Parameter] = []   # ordered unique parameter refs
        self._param_ids: Dict[int, int] = {}  # id(param) -> index
        self._vid = _GLOBAL_VID
        self._version = 0
        self._loss_vid: Optional[int] = None
        self._grad_of: Dict[int, int] = {}    # param index -> grad vid
        self._var_grads: List[Tuple[int, int]] = []  # (target vid, wrt vid)
        self._optimizer = None
        self.random_seed = 0
        self.id = next(Program._ids)

    # ---- build helpers ---------------------------------------------------
    def _new_var(self, aval, name=None) -> Variable:
        vid = next(self._vid)
        v = Variable(aval, name=name or f"tmp_{self.id}_{vid}", vid=vid)
        v.program = self
        self._vars[vid] = v
        self._version += 1
        return v

    def _param_index(self, p: Parameter) -> int:
        idx = self._param_ids.get(id(p))
        if idx is None:
            idx = len(self._params)
            self._params.append(p)
            self._param_ids[id(p)] = idx
            if not p.name:
                p.name = f"param_{self.id}_{idx}"
        return idx

    def global_block(self) -> Block:
        return Block(self)

    def list_vars(self):
        return list(self._vars.values())

    def all_parameters(self):
        return list(self._params)

    def clone(self, for_test: bool = False):
        """Shallow structural clone (reference Program.clone). The recorded
        graph is immutable-by-append, so clones share nodes up to the clone
        point; `for_test` drops the attached optimizer/backward section."""
        p = Program()
        p._nodes = list(self._nodes)
        p._vars = dict(self._vars)
        p._feed_vars = list(self._feed_vars)
        p._key_vars = list(self._key_vars)
        p._params = list(self._params)
        p._param_ids = dict(self._param_ids)
        p._vid = _GLOBAL_VID
        p._version = self._version
        if not for_test:
            p._loss_vid = self._loss_vid
            p._grad_of = dict(self._grad_of)
            p._optimizer = self._optimizer
        else:
            # strip train-only ops (reference: clone(for_test=True) flips
            # is_test attrs / removes dropout ops): dropout becomes identity
            # on its data input (upscale_in_train semantics → inference is a
            # pass-through)
            def _identity_first(a, *rest):
                return a
            p._nodes = [
                OpNode(n.name, _identity_first, {}, [n.inputs[0]], n.out_vids, n.multi)
                if n.name in ("dropout", "alpha_dropout") else n
                for n in p._nodes
            ]
            p._version += 1
        p.random_seed = self.random_seed
        return p

    def to_readable_code(self) -> str:
        lines = [f"Program(id={self.id}, ops={len(self._nodes)})"]
        for v in self._feed_vars:
            lines.append(f"  feed {v.feed_name}: shape={list(v._data.shape)} "
                         f"dtype={v._data.dtype}")
        for n in self._nodes:
            ins = ", ".join(
                f"v{ref}" if kind == "v" else ("param%d" % ref if kind == "p" else "const")
                for kind, ref in n.inputs)
            lines.append(f"  {n.name}({ins}) -> {n.out_vids}")
        return "\n".join(lines)

    __str__ = to_readable_code


# ---------------------------------------------------------------- mode state
_static_mode = False
_program_stack: List[Tuple[Program, Program]] = []  # (main, startup)
_default_main = Program()
_default_startup = Program()


def in_static_mode() -> bool:
    return _static_mode


def enable_static():
    """Switch to graph-building mode (reference: paddle.enable_static)."""
    global _static_mode
    _static_mode = True
    _install_hooks()


def disable_static():
    global _static_mode
    _static_mode = False


def default_main_program() -> Program:
    return _program_stack[-1][0] if _program_stack else _default_main


def default_startup_program() -> Program:
    return _program_stack[-1][1] if _program_stack else _default_startup


def reset_default_programs():
    global _default_main, _default_startup
    _default_main = Program()
    _default_startup = Program()


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    """Route subsequent recording into `main_program` (reference:
    fluid/framework.py program_guard)."""
    _program_stack.append((main_program, startup_program or Program()))
    try:
        yield
    finally:
        _program_stack.pop()


def data(name: str, shape: Sequence[int], dtype="float32", lod_level=0) -> Variable:
    """Declare a feed placeholder (reference: paddle.static.data,
    fluid/data.py). Dims given as None/-1 are placeholders for the batch
    dimension; actual shapes flow in at Executor.run time (the replay is
    shape-polymorphic — each distinct feed shape compiles once, mirroring
    the reference's _ExecutorCache keyed on feed)."""
    del lod_level
    prog = default_main_program()
    dt = convert_dtype(dtype) or jnp.float32
    build_shape = tuple(1 if (s is None or s < 0) else int(s) for s in shape)
    v = prog._new_var(jax.ShapeDtypeStruct(build_shape, dt), name=name)
    v.is_feed = True
    v.feed_name = name
    v.declared_shape = tuple(-1 if (s is None or s < 0) else int(s) for s in shape)
    prog._feed_vars.append(v)
    return v


# ---------------------------------------------------------------- recording
def _key_aval():
    return jax.eval_shape(lambda: jax.random.key(0))


def _symbolic_key():
    """Fresh symbolic RNG key Variable, fed a new key every Executor.run —
    this is how static-mode dropout gets per-step randomness (the reference
    plumbs a seed tensor into dropout kernels; we plumb a threefry key)."""
    prog = default_main_program()
    v = prog._new_var(_key_aval(), name=f"rng_key_{len(prog._key_vars)}")
    v.is_key = True
    prog._key_vars.append(v)
    return v


_record_suppressed = False


@contextlib.contextmanager
def suppress_recording():
    """Run ops eagerly even in static mode — used while REPLAYING recorded
    control-flow bodies (while_loop), where captured Variables temporarily
    hold real/traced arrays."""
    global _record_suppressed
    prev = _record_suppressed
    _record_suppressed = True
    try:
        yield
    finally:
        _record_suppressed = prev


def _recording_active() -> bool:
    return _static_mode and not _record_suppressed


def _record_apply(name, fn, tensor_args, static_kwargs, n_outputs):
    """The static-mode branch of core.tensor.apply_op: append an OpNode when
    any input is symbolic; otherwise fall through to eager (returns
    NotImplemented)."""
    if not _recording_active() or not any(
            isinstance(a, Variable) for a in tensor_args):
        return NotImplemented
    prog = default_main_program()
    inputs = []
    avals = []
    for a in tensor_args:
        if isinstance(a, Variable):
            inputs.append(("v", a.vid))
            avals.append(a._data)
        elif isinstance(a, Parameter):
            inputs.append(("p", prog._param_index(a)))
            avals.append(jax.ShapeDtypeStruct(a._data.shape, a._data.dtype))
        elif isinstance(a, Tensor):
            inputs.append(("c", a._data))
            avals.append(a._data)
        else:
            arr = a if isinstance(a, jax.Array) else jnp.asarray(a)
            inputs.append(("c", arr))
            avals.append(arr)

    amp_state = None
    from ..amp.auto_cast import get_amp_state, amp_dest_dtype, _should_cast
    st = get_amp_state()
    if st.enabled:
        amp_state = st
        dest = amp_dest_dtype(name, st)
        if dest is not None:
            avals = [jax.ShapeDtypeStruct(a.shape, dest)
                     if hasattr(a, "dtype") and _should_cast(a.dtype, dest)
                     else a for a in avals]

    out_avals = jax.eval_shape(partial(fn, **static_kwargs), *avals)
    multi = isinstance(out_avals, (tuple, list))
    outs_t = tuple(out_avals) if multi else (out_avals,)
    out_vars = tuple(prog._new_var(o, name=f"{name}_{prog._version}") for o in outs_t)
    prog._nodes.append(OpNode(name, fn, static_kwargs, inputs,
                              tuple(v.vid for v in out_vars),
                              multi or n_outputs is not None,
                              amp_state=amp_state))
    if len(out_vars) == 1 and n_outputs is None:
        return out_vars[0]
    return out_vars


def _op_key_hook():
    if _recording_active():
        return _symbolic_key()
    return None


def _install_hooks():
    tensor_mod._static_record = _record_apply
    random_mod._op_key_hook = _op_key_hook


# ---------------------------------------------------------------- backward
def append_backward(loss: Variable, parameter_list=None, no_grad_set=None):
    """Mark the loss and materialize grad Variables for every trainable
    parameter the program references (reference: fluid/backward.py
    append_backward). The actual differentiation is jax.value_and_grad over
    the replayed program at Executor build time — no per-op grad graph needs
    constructing (SURVEY §7: autodiff comes from the functional substrate)."""
    if not isinstance(loss, Variable):
        raise TypeError("append_backward expects a static Variable loss")
    # resolve the program that owns the loss (reference: loss.block.program),
    # not the default — minimize() may be called outside the program_guard
    prog = loss.program or default_main_program()
    prog._loss_vid = loss.vid
    if parameter_list is not None:
        wanted = {id(p) for p in parameter_list}
        params = [p for p in prog._params if id(p) in wanted]
    else:
        params = [p for p in prog._params if not p.stop_gradient]
    pairs = []
    for p in params:
        idx = prog._param_index(p)
        gv = prog._grad_of.get(idx)
        if gv is None:
            g = prog._new_var(jax.ShapeDtypeStruct(p._data.shape, p._data.dtype),
                              name=(p.name or f"param{idx}") + "@GRAD")
            prog._grad_of[idx] = g.vid
            gv = g.vid
        pairs.append((p, prog._vars[gv]))
    return pairs


def gradients(targets, inputs, target_gradients=None):
    """d(sum targets)/d(inputs) as new graph Variables (reference:
    paddle.static.gradients)."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if len(targets) != 1:
        raise NotImplementedError("gradients: single target supported")
    t = targets[0]
    prog = (t.program if isinstance(t, Variable) and t.program is not None
            else default_main_program())
    outs = []
    for x in inputs:
        if not isinstance(x, Variable):
            raise TypeError("gradients inputs must be Variables")
        g = prog._new_var(jax.ShapeDtypeStruct(x._data.shape, x._data.dtype),
                          name=(x.name or "x") + "@GRAD")
        prog._var_grads.append((t.vid, x.vid, g.vid))
        outs.append(g)
    return outs
