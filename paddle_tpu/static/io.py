"""Inference model serialization — AOT-compiled StableHLO artifacts.

TPU-native redesign of the reference's save/load_inference_model
(python/paddle/static/io.py → __model__ ProgramDesc + params files, consumed
by AnalysisPredictor, SURVEY §2.4): the portable artifact here is the XLA
ecosystem's native one — a serialized `jax.export` StableHLO module (the
replayed Program lowered and captured AOT) plus an .npz of parameter values
and a small JSON header for feed/fetch metadata. Loading needs no IR passes
or op converters: deserialize + call.
"""
from __future__ import annotations

import json
import os
from typing import List, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..core.tensor import Tensor
from .program import Program, Variable, default_main_program
from .executor import Executor


def normalize_program(program, feed_vars, fetch_vars):
    return program


def _export_platforms():
    plats = ["cpu"]
    try:
        if any(d.platform in ("tpu", "axon") for d in jax.devices()):
            plats.append(jax.devices()[0].platform)
    except RuntimeError:
        pass
    return tuple(plats)


def save_inference_model(path_prefix: str, feed_vars: Sequence[Variable],
                         fetch_vars: Sequence[Variable], executor: Executor,
                         program: Program = None):
    """reference: paddle.static.save_inference_model (static/io.py)."""
    program = program or default_main_program()
    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)

    infer = program.clone(for_test=True)
    # bind current parameter values as constants into the exported module
    fetch_vids = tuple(v.vid for v in fetch_vars)
    exe = Executor()
    fn = exe._build(infer, fetch_vids, train=False, feed_vars=feed_vars)

    diff_params = [p for p in infer._params if not p.stop_gradient
                   and np.issubdtype(np.dtype(p._data.dtype), np.floating)]
    _diff_ids = {id(p) for p in diff_params}
    const_params = [p for p in infer._params if id(p) not in _diff_ids]
    keys = tuple(jax.random.key(infer.random_seed + i)
                 for i in range(len(infer._key_vars)))

    def serving(*feeds):
        return fn(tuple(p._data for p in diff_params),
                  tuple(p._data for p in const_params), keys, *feeds)

    # feed dims declared -1/None export as symbolic dims (jax shape
    # polymorphism) — the artifact then serves any batch size, the analog of
    # the reference predictor's dynamic-shape support (TRT dynamic shapes)
    def _avals(symbolic):
        out = []
        scope = jax_export.SymbolicScope() if symbolic else None
        for i, v in enumerate(feed_vars):
            decl = v.declared_shape or tuple(v._data.shape)
            if symbolic and any(d == -1 for d in decl):
                spec = ",".join(f"d{i}_{j}" if d == -1 else str(d)
                                for j, d in enumerate(decl))
                shape = jax_export.symbolic_shape(spec, scope=scope)
            else:
                shape = tuple(v._data.shape)
            out.append(jax.ShapeDtypeStruct(shape, v._data.dtype))
        return out

    exported = None
    for symbolic in (True, False):
        try:
            exported = jax_export.export(jax.jit(serving),
                                         platforms=_export_platforms())(*_avals(symbolic))
            break
        except Exception:
            continue
    if exported is None:
        exported = jax_export.export(jax.jit(serving))(*_avals(False))

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    meta = {
        "feed_names": [v.feed_name or v.name for v in feed_vars],
        "feed_shapes": [list(v._data.shape) for v in feed_vars],
        "feed_dtypes": [str(np.dtype(v._data.dtype)) for v in feed_vars],
        "fetch_names": [v.name for v in fetch_vars],
    }
    with open(path_prefix + ".pdmeta", "w") as f:
        json.dump(meta, f)
    # params are baked into the module; keep a sidecar copy for tooling parity
    np.savez(path_prefix + ".pdiparams.npz",
             **{(p.name or f"param_{i}"): np.asarray(p._data)
                for i, p in enumerate(program._params)})
    return path_prefix


class _LoadedInferenceProgram:
    """Replayable artifact: Executor.run(program=this, feed=..., fetch_list=...)
    works, and `.run(feed_arrays)` calls directly."""

    def __init__(self, exported, meta):
        self._exported = exported
        self.meta = meta
        self.feed_target_names = meta["feed_names"]
        self.fetch_target_names = meta["fetch_names"]

    def run(self, *feeds):
        outs = self._exported.call(*[jnp.asarray(f) for f in feeds])
        return [np.asarray(o) for o in outs]


def load_inference_model(path_prefix: str, executor: Executor = None):
    """reference: paddle.static.load_inference_model — returns
    (program, feed_target_names, fetch_targets)."""
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path_prefix + ".pdmeta") as f:
        meta = json.load(f)
    prog = _LoadedInferenceProgram(exported, meta)
    return prog, meta["feed_names"], meta["fetch_names"]


def save(program: Program, path_prefix: str):
    """Persist parameter values (reference: paddle.static.save →
    .pdparams/.pdopt). Program structure is python-held; parameters are the
    durable state."""
    np.savez(path_prefix + ".pdparams.npz",
             **{(p.name or f"param_{i}"): np.asarray(p._data)
                for i, p in enumerate(program._params)})


def load(program: Program, path_prefix: str, executor=None, var_list=None):
    data = np.load(path_prefix + ".pdparams.npz")
    for i, p in enumerate(program._params):
        key = p.name or f"param_{i}"
        if key in data:
            p._data = jnp.asarray(data[key])
