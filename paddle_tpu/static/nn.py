"""paddle.static.nn — graph-building layer functions.

The reference keeps a separate static layer API (python/paddle/static/nn/,
fluid/layers/) that appends ops + creates parameters on the default program.
Here the dynamic `paddle_tpu.nn` layers already split cleanly into eager
parameter creation (the implicit startup program) + recordable ops, so these
functions simply construct a layer and call it on the symbolic input — one
layer implementation serves both modes, the way PHI infermeta/kernels are
shared between the reference's two modes.
"""
from __future__ import annotations

import numpy as np

from .. import nn as dyn_nn
from .program import Variable, default_main_program


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference: paddle.static.nn.fc (static/nn/common.py)."""
    in_shape = list(x._data.shape)
    in_features = _prod(in_shape[num_flatten_dims:])
    if num_flatten_dims != len(in_shape) - 1 or in_features != in_shape[-1]:
        from ..core import ops as _ops
        x = _ops.reshape(x, in_shape[:num_flatten_dims] + [in_features])
    layer = dyn_nn.Linear(in_features, size,
                          bias_attr=bias_attr if bias_attr is not None else None)
    out = layer(x)
    if activation:
        out = getattr(dyn_nn.functional, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, weight_attr=None,
              name=None):
    """reference: paddle.static.nn.embedding."""
    layer = dyn_nn.Embedding(size[0], size[1], padding_idx=padding_idx)
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, bias_attr=None, name=None, data_format="NCHW"):
    in_ch = input._data.shape[1 if data_format == "NCHW" else -1]
    layer = dyn_nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                          padding=padding, dilation=dilation, groups=groups,
                          data_format=data_format)
    return layer(input)


def batch_norm(input, epsilon=1e-5, momentum=0.9, data_layout="NCHW",
               is_test=False, name=None):
    ch = input._data.shape[1 if data_layout == "NCHW" else -1]
    layer = dyn_nn.BatchNorm(ch, momentum=momentum, epsilon=epsilon,
                             data_layout=data_layout)
    if is_test:
        layer.eval()
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, name=None):
    shape = list(input._data.shape)[begin_norm_axis:]
    layer = dyn_nn.LayerNorm(shape, epsilon=epsilon)
    return layer(input)


def dropout(x, dropout_prob=0.5, is_test=False, name=None):
    return dyn_nn.functional.dropout(x, p=dropout_prob, training=not is_test)


def cond(pred, true_fn, false_fn):
    """Static conditional (reference: paddle.static.nn.cond → conditional
    block ops). On TPU this is lax.cond over the recorded branches — both
    branches must be recordable pure functions of closed-over Variables."""
    import jax
    from ..core.tensor import Tensor, apply_op

    t_out = true_fn()
    f_out = false_fn()

    def fn(p, t, f):
        return jax.lax.cond(p.reshape(()).astype(bool), lambda: t, lambda: f)
    return apply_op("cond", fn, [pred, t_out, f_out])
