"""paddle.static.nn — graph-building layer functions.

The reference keeps a separate static layer API (python/paddle/static/nn/,
fluid/layers/) that appends ops + creates parameters on the default program.
Here the dynamic `paddle_tpu.nn` layers already split cleanly into eager
parameter creation (the implicit startup program) + recordable ops, so these
functions simply construct a layer and call it on the symbolic input — one
layer implementation serves both modes, the way PHI infermeta/kernels are
shared between the reference's two modes.
"""
from __future__ import annotations

import numpy as np

from .. import nn as dyn_nn
from .program import Variable, default_main_program


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference: paddle.static.nn.fc (static/nn/common.py)."""
    in_shape = list(x._data.shape)
    in_features = _prod(in_shape[num_flatten_dims:])
    if num_flatten_dims != len(in_shape) - 1 or in_features != in_shape[-1]:
        from ..core import ops as _ops
        x = _ops.reshape(x, in_shape[:num_flatten_dims] + [in_features])
    layer = dyn_nn.Linear(in_features, size, weight_attr=weight_attr,
                          bias_attr=bias_attr)
    out = layer(x)
    if activation:
        out = getattr(dyn_nn.functional, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, weight_attr=None,
              name=None):
    """reference: paddle.static.nn.embedding."""
    layer = dyn_nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                             weight_attr=weight_attr)
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, bias_attr=None, name=None, data_format="NCHW"):
    in_ch = input._data.shape[1 if data_format == "NCHW" else -1]
    layer = dyn_nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                          padding=padding, dilation=dilation, groups=groups,
                          data_format=data_format)
    return layer(input)


def batch_norm(input, epsilon=1e-5, momentum=0.9, data_layout="NCHW",
               is_test=False, name=None):
    ch = input._data.shape[1 if data_layout == "NCHW" else -1]
    layer = dyn_nn.BatchNorm(ch, momentum=momentum, epsilon=epsilon,
                             data_layout=data_layout)
    if is_test:
        layer.eval()
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, name=None):
    shape = list(input._data.shape)[begin_norm_axis:]
    layer = dyn_nn.LayerNorm(shape, epsilon=epsilon)
    return layer(input)


def dropout(x, dropout_prob=0.5, is_test=False, name=None):
    return dyn_nn.functional.dropout(x, p=dropout_prob, training=not is_test)


def cond(pred, true_fn, false_fn):
    """Static conditional (reference: paddle.static.nn.cond → conditional
    block ops). On TPU this is lax.cond over the recorded branches — both
    branches must be recordable pure functions of closed-over Variables."""
    import jax
    from ..core.tensor import Tensor, apply_op

    t_out = true_fn()
    f_out = false_fn()

    def fn(p, t, f):
        return jax.lax.cond(p.reshape(()).astype(bool), lambda: t, lambda: f)
    return apply_op("cond", fn, [pred, t_out, f_out])


def while_loop(cond_fn, body_fn, loop_vars):
    """Static while loop (reference: paddle.static.nn.while_loop → the While
    op over a sub-block, framework/operators/controlflow/while_op). The
    WHOLE loop records as one op whose replay is lax.while_loop
    (compiler-friendly control flow, SURVEY §7).

    Closures over outer Variables (the reference's sub-block reading parent-
    block vars) are supported: a probe trace discovers which outer Variables
    the body/cond read; they become extra inputs of the recorded op, and at
    replay their values are swapped in while recording is suppressed.
    """
    import jax
    from ..core.tensor import Tensor, apply_op
    from .program import (Variable, Program, program_guard, in_static_mode,
                          suppress_recording)

    loop_vars = list(loop_vars)
    n = len(loop_vars)

    captures = []
    if in_static_mode():
        # probe: run cond/body on fresh Variables inside a throwaway program;
        # any ("v", vid) input NOT created by the probe is an outer capture
        probe = Program()
        with program_guard(probe):
            pv = [probe._new_var(jax.ShapeDtypeStruct(
                tuple(v._data.shape), v._data.dtype)) for v in loop_vars]
            cond_fn(*pv)
            body_fn(*pv)
        probe_vids = {v.vid for v in probe._vars.values()}
        seen = {}
        for node in probe._nodes:
            for kind, ref in node.inputs:
                if kind == "v" and ref not in probe_vids:
                    seen[ref] = True
        # resolve capture vids back to live Variable objects
        from .program import default_main_program
        outer = default_main_program()
        captures = [outer._vars[vid] for vid in seen if vid in outer._vars]

    def fn(*arrays):
        loop_arrs, cap_arrs = arrays[:n], arrays[n:]

        def run_with_captures(f, vs):
            saved = [c._data for c in captures]
            for c, a in zip(captures, cap_arrs):
                c._data = a
            try:
                with suppress_recording():
                    return f(*[Tensor(v) for v in vs])
            finally:
                for c, s in zip(captures, saved):
                    c._data = s

        def c(vs):
            t = run_with_captures(cond_fn, vs)
            t = t._data if isinstance(t, Tensor) else t
            return t.reshape(()).astype(bool)

        def b(vs):
            out = run_with_captures(body_fn, vs)
            out = out if isinstance(out, (tuple, list)) else (out,)
            return tuple(o._data if isinstance(o, Tensor) else o for o in out)

        return jax.lax.while_loop(c, b, tuple(loop_arrs))

    out = apply_op("while_loop", fn, loop_vars + captures, n_outputs=n)
    out = out[:n] if isinstance(out, tuple) else (out,)
    return list(out)


def case(pred_fn_pairs, default=None):
    """reference: paddle.static.nn.case — first true predicate wins."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if not rest:
        if default is None:
            return fn()
        return cond(pred, fn, default)
    return cond(pred, fn, lambda: case(rest, default))


def switch_case(branch_index, branch_fns, default=None):
    """reference: paddle.static.nn.switch_case — dispatch on an int index.
    Replays as lax.switch (one compiled branch table)."""
    import jax
    from ..core.tensor import Tensor, apply_op

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
        keys = [k for k, _ in items]
        fns = [f for _, f in items]
    else:
        keys = list(range(len(branch_fns)))
        fns = list(branch_fns)
    outs = [f() for f in fns]
    if default is not None:
        outs.append(default())
    keys_arr = keys

    def fn(idx, *branch_vals):
        import jax.numpy as jnp
        idx = idx.reshape(()).astype(jnp.int32)
        # unmatched index -> default if given, else the LAST branch
        # (reference switch_case semantics, static/nn/control_flow.py)
        sel = jnp.int32(len(branch_vals) - 1)
        for i, k in enumerate(keys_arr):
            sel = jnp.where(idx == k, jnp.int32(i), sel)
        return jax.lax.switch(sel, [lambda v=v: v for v in branch_vals])
    return apply_op("switch_case", fn, [branch_index] + outs)


# sequence_* LoD family (reference: static/nn/sequence_lod.py) — TPU-native
# padded-dense + lengths representation; see static/sequence.py
from .sequence import (  # noqa: F401,E402
    sequence_conv, sequence_softmax, sequence_pool, sequence_concat,
    sequence_first_step, sequence_last_step, sequence_slice,
    sequence_expand, sequence_expand_as, sequence_pad, sequence_unpad,
    sequence_reshape, sequence_scatter, sequence_enumerate,
    sequence_reverse,
)
from ..nn.functional import sequence_mask  # noqa: F401,E402
