"""paddle.text analog — NLP utilities (reference: python/paddle/text/,
SURVEY §2.3: datasets + ViterbiDecoder).

The decoder is the real compute piece: CRF decoding as a lax.scan over the
sequence (compiler-friendly control flow — the reference backs it with the
viterbi_decode PHI kernel, phi/kernels/cpu/viterbi_decode_kernel.cc).
Dataset classes read local corpus files; automatic downloads are disabled
in this environment (zero egress), matching the reference's DATA_HOME
layout when files are present.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply_op
from ..nn.layer import Layer


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decode → (scores, best paths).

    reference: paddle.text.viterbi_decode (text/viterbi_decode.py) over the
    viterbi_decode op. potentials: [B, T, N] emissions; transition: [N, N]
    (with BOS=N-2/EOS=N-1 rows when include_bos_eos_tag, matching the
    reference's tag convention).
    """

    def fn(emis, trans):
        B, T, N = emis.shape
        if include_bos_eos_tag:
            # BOS transitions initialize step 0; EOS added at the end
            init = emis[:, 0, :] + trans[N - 2, :][None, :]
        else:
            init = emis[:, 0, :]

        def step(carry, e_t):
            score = carry  # [B, N]
            # score[b, j] = max_i score[b,i] + trans[i,j] + e_t[b,j]
            cand = score[:, :, None] + trans[None, :, :]
            best = jnp.max(cand, axis=1) + e_t
            back = jnp.argmax(cand, axis=1)
            return best, back

        final, backs = lax.scan(step, init, jnp.swapaxes(emis, 0, 1)[1:])
        if include_bos_eos_tag:
            final = final + trans[:, N - 1][None, :]
        scores = jnp.max(final, axis=-1)
        last = jnp.argmax(final, axis=-1)

        def backtrace(carry, back_t):
            tag = carry
            prev = jnp.take_along_axis(back_t, tag[:, None], axis=1)[:, 0]
            return prev, prev

        _, path_rev = lax.scan(backtrace, last, backs, reverse=True)
        paths = jnp.concatenate([path_rev, last[None, :]], axis=0)
        return scores, jnp.swapaxes(paths, 0, 1).astype(jnp.int64)

    return apply_op("viterbi_decode", fn, [potentials, transition_params],
                    n_outputs=2)


class ViterbiDecoder(Layer):
    """reference: paddle.text.ViterbiDecoder layer."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self._include = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self._include)


class _LocalDataset:
    """Base for corpus datasets: requires data_file on disk (no egress)."""

    def __init__(self, data_file: Optional[str], mode: str = "train"):
        self.mode = mode
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{type(self).__name__}: pass data_file= pointing at a local "
                "copy of the corpus; automatic download is unavailable in "
                "this environment (reference datasets download to DATA_HOME)")
        self.data_file = data_file


class Imdb(_LocalDataset):
    """reference: paddle.text.datasets.Imdb (sentiment corpus)."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        super().__init__(data_file, mode)


class Conll05st(_LocalDataset):
    """reference: paddle.text.datasets.Conll05st (SRL corpus)."""


class Movielens(_LocalDataset):
    """reference: paddle.text.datasets.Movielens."""


class UCIHousing(_LocalDataset):
    """reference: paddle.text.datasets.UCIHousing."""


class WMT14(_LocalDataset):
    """reference: paddle.text.datasets.WMT14."""


class WMT16(_LocalDataset):
    """reference: paddle.text.datasets.WMT16."""


class Imikolov(_LocalDataset):
    """reference: paddle.text.datasets.Imikolov."""


datasets = type("datasets", (), {
    "Imdb": Imdb, "Conll05st": Conll05st, "Movielens": Movielens,
    "UCIHousing": UCIHousing, "WMT14": WMT14, "WMT16": WMT16,
    "Imikolov": Imikolov,
})
