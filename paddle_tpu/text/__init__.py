"""paddle.text analog — NLP utilities (reference: python/paddle/text/,
SURVEY §2.3: datasets + ViterbiDecoder).

The decoder is the real compute piece: CRF decoding as a lax.scan over the
sequence (compiler-friendly control flow — the reference backs it with the
viterbi_decode PHI kernel, phi/kernels/cpu/viterbi_decode_kernel.cc).
Dataset classes read local corpus files; automatic downloads are disabled
in this environment (zero egress), matching the reference's DATA_HOME
layout when files are present.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply_op
from ..nn.layer import Layer


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decode → (scores, best paths).

    reference: paddle.text.viterbi_decode (text/viterbi_decode.py) over the
    viterbi_decode op. potentials: [B, T, N] emissions; transition: [N, N]
    (with BOS=N-2/EOS=N-1 rows when include_bos_eos_tag, matching the
    reference's tag convention).
    """

    def fn(emis, trans):
        B, T, N = emis.shape
        if include_bos_eos_tag:
            # BOS transitions initialize step 0; EOS added at the end
            init = emis[:, 0, :] + trans[N - 2, :][None, :]
        else:
            init = emis[:, 0, :]

        def step(carry, e_t):
            score = carry  # [B, N]
            # score[b, j] = max_i score[b,i] + trans[i,j] + e_t[b,j]
            cand = score[:, :, None] + trans[None, :, :]
            best = jnp.max(cand, axis=1) + e_t
            back = jnp.argmax(cand, axis=1)
            return best, back

        final, backs = lax.scan(step, init, jnp.swapaxes(emis, 0, 1)[1:])
        if include_bos_eos_tag:
            final = final + trans[:, N - 1][None, :]
        scores = jnp.max(final, axis=-1)
        last = jnp.argmax(final, axis=-1)

        def backtrace(carry, back_t):
            tag = carry
            prev = jnp.take_along_axis(back_t, tag[:, None], axis=1)[:, 0]
            return prev, prev

        _, path_rev = lax.scan(backtrace, last, backs, reverse=True)
        paths = jnp.concatenate([path_rev, last[None, :]], axis=0)
        return scores, jnp.swapaxes(paths, 0, 1).astype(jnp.int64)

    return apply_op("viterbi_decode", fn, [potentials, transition_params],
                    n_outputs=2)


class ViterbiDecoder(Layer):
    """reference: paddle.text.ViterbiDecoder layer."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self._include = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self._include)


class _LocalDataset:
    """Base for corpus datasets: requires data_file on disk (no egress)."""

    def __init__(self, data_file: Optional[str], mode: str = "train"):
        self.mode = mode
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{type(self).__name__}: pass data_file= pointing at a local "
                "copy of the corpus; automatic download is unavailable in "
                "this environment (reference datasets download to DATA_HOME)")
        self.data_file = data_file


class Imdb(_LocalDataset):
    """reference: paddle.text.datasets.Imdb (aclImdb sentiment corpus).

    data_file: either an aclImdb-style directory root
    ({mode}/pos/*.txt, {mode}/neg/*.txt) or a TSV file of "label<TAB>text"
    lines (label 0/1). Tokenization and the frequency-cutoff vocab follow
    the reference (imdb.py _build_work_dict: keep words with freq >= cutoff,
    sorted by (-freq, word))."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 word_idx=None):
        super().__init__(data_file, mode)
        import re
        tok = re.compile(r"[a-z]+")

        def read_dir(split):
            ds, ls = [], []
            for label, sub in ((1, "pos"), (0, "neg")):
                d = os.path.join(self.data_file, split, sub)
                for fn in sorted(os.listdir(d)) if os.path.isdir(d) else []:
                    with open(os.path.join(d, fn), errors="ignore") as f:
                        ds.append(tok.findall(f.read().lower()))
                        ls.append(label)
            return ds, ls

        def read_tsv(path):
            ds, ls = [], []
            with open(path, errors="ignore") as f:
                for line in f:
                    lab, _, text = line.partition("\t")
                    if not text:
                        continue
                    ds.append(tok.findall(text.lower()))
                    ls.append(int(lab))
            return ds, ls

        if os.path.isdir(self.data_file):
            docs, labels = read_dir(mode)
            # vocab ALWAYS from the train corpus so train/test ids agree
            # (reference: imdb.py builds word_idx from the train pattern)
            if word_idx is not None or mode == "train":
                vocab_docs = docs
            else:
                vocab_docs = read_dir("train")[0]
        else:
            docs, labels = read_tsv(self.data_file)
            if word_idx is not None or mode == "train":
                vocab_docs = docs
            else:
                # same rule for TSV input: ids must come from the TRAIN
                # corpus. Look for the sibling train file (test.tsv ->
                # train.tsv, basename only — the mode string may also occur
                # in directory names); else the caller must share word_idx.
                head, base = os.path.split(self.data_file)
                # replace only the LAST occurrence of the mode token in the
                # basename (a name like "protest_test.tsv" contains it twice)
                pre, hit, post = base.rpartition(mode)
                sib_base = pre + "train" + post if hit else base
                sib = os.path.join(head, sib_base)
                if sib_base != base and os.path.exists(sib):
                    vocab_docs = read_tsv(sib)[0]
                else:
                    raise ValueError(
                        "Imdb(TSV, mode=%r): cannot locate the train file to "
                        "build a train-consistent vocab; pass word_idx= from "
                        "the train dataset" % mode)
        if word_idx is not None:
            self.word_idx = dict(word_idx)
        else:
            freq = {}
            for d in vocab_docs:
                for w in d:
                    freq[w] = freq.get(w, 0) + 1
            kept = sorted(((w, c) for w, c in freq.items() if c >= cutoff),
                          key=lambda x: (-x[1], x[0]))
            self.word_idx = {w: i for i, (w, _) in enumerate(kept)}
            self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(w, unk) for w in d],
                              np.int64) for d in docs]
        self.labels = np.array(labels, np.int64)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]

    def __len__(self):
        return len(self.docs)


class Imikolov(_LocalDataset):
    """reference: paddle.text.datasets.Imikolov (PTB language modelling).

    data_file: plain text, one sentence per line (the extracted
    ptb.{train,valid}.txt). NGRAM mode yields window_size-grams; SEQ mode
    yields (<s>+sent, sent+<e>) pairs — the reference's exact contract
    (imikolov.py:132-172), including the vocab rule: freq > min_word_freq,
    sorted by (-freq, word), <unk> last."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50):
        super().__init__(data_file, mode)
        self.data_type = data_type.upper()
        self.window_size = window_size
        freq = {"<s>": 0, "<e>": 0}
        lines = []
        with open(self.data_file, errors="ignore") as f:
            for line in f:
                ws = line.strip().split()
                lines.append(ws)
                for w in ["<s>", "<e>"] + ws:
                    freq[w] = freq.get(w, 0) + 1
        freq.pop("<unk>", None)
        kept = sorted(((w, c) for w, c in freq.items() if c > min_word_freq),
                      key=lambda x: (-x[1], x[0]))
        self.word_idx = {w: i for i, (w, _) in enumerate(kept)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for ws in lines:
            if self.data_type == "NGRAM":
                assert self.window_size > -1, "Invalid gram length"
                l2 = ["<s>"] + ws + ["<e>"]
                if len(l2) >= self.window_size:
                    ids = [self.word_idx.get(w, unk) for w in l2]
                    for i in range(self.window_size, len(ids) + 1):
                        self.data.append(tuple(ids[i - self.window_size:i]))
            elif self.data_type == "SEQ":
                ids = [self.word_idx.get(w, unk) for w in ws]
                src = [self.word_idx.get("<s>", unk)] + ids
                trg = ids + [self.word_idx.get("<e>", unk)]
                if self.window_size > 0 and len(src) > self.window_size:
                    continue
                self.data.append((src, trg))
            else:
                raise ValueError(f"unknown data_type {data_type}")

    def __getitem__(self, i):
        return tuple(np.array(d) for d in self.data[i])

    def __len__(self):
        return len(self.data)


class UCIHousing(_LocalDataset):
    """reference: paddle.text.datasets.UCIHousing — space-separated
    14-column file; per-feature (x-avg)/(max-min) normalization and the
    80/20 train/test split are the reference's exact math
    (uci_housing.py:107-121)."""

    def __init__(self, data_file=None, mode="train"):
        super().__init__(data_file, mode)
        feature_num = 14
        data = np.fromfile(self.data_file, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maximums, minimums, avgs = (data.max(0), data.min(0),
                                    data.sum(0) / data.shape[0])
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
        offset = int(data.shape[0] * 0.8)
        self.data = data[:offset] if mode == "train" else data[offset:]
        self.dtype = "float32"

    def __getitem__(self, idx):
        d = self.data[idx]
        return (np.array(d[:-1]).astype(self.dtype),
                np.array(d[-1:]).astype(self.dtype))

    def __len__(self):
        return len(self.data)


class Movielens(_LocalDataset):
    """reference: paddle.text.datasets.Movielens (ml-1m). data_file: a
    directory containing ratings.dat / users.dat / movies.dat in the
    ::-separated ml-1m format; yields (user_id, gender, age, job,
    movie_id, title_ids, categories, rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        super().__init__(data_file, mode)
        root = self.data_file
        movies, self.categories_dict, self.movie_title_dict = {}, {}, {}
        with open(os.path.join(root, "movies.dat"), errors="ignore") as f:
            for line in f:
                mid, title, cats = line.strip().split("::")
                for c in cats.split("|"):
                    self.categories_dict.setdefault(c, len(self.categories_dict))
                tw = title.split()
                for w in tw:
                    self.movie_title_dict.setdefault(w, len(self.movie_title_dict))
                movies[int(mid)] = (
                    [self.categories_dict[c] for c in cats.split("|")],
                    [self.movie_title_dict[w] for w in tw])
        users = {}
        with open(os.path.join(root, "users.dat"), errors="ignore") as f:
            for line in f:
                uid, gender, age, job, _zip = line.strip().split("::")
                users[int(uid)] = (0 if gender == "M" else 1, int(age), int(job))
        rng = np.random.RandomState(rand_seed)
        self.data = []
        with open(os.path.join(root, "ratings.dat"), errors="ignore") as f:
            for line in f:
                uid, mid, rating, _ts = line.strip().split("::")
                uid, mid = int(uid), int(mid)
                if mid not in movies or uid not in users:
                    continue
                is_test = rng.rand() < test_ratio
                if (mode == "test") != is_test:
                    continue
                g, a, j = users[uid]
                cats, title = movies[mid]
                self.data.append((uid, g, a, j, mid, title, cats,
                                  float(rating)))

    def __getitem__(self, i):
        return tuple(np.array(d) for d in self.data[i])

    def __len__(self):
        return len(self.data)


class _ParallelCorpus(_LocalDataset):
    """Shared WMT loader: data_file is a TSV of "src<TAB>tgt" sentence
    pairs; builds per-side vocabs capped at dict_size (by frequency, specials
    first) and yields (src_ids, trg_ids, trg_next) like the reference's
    wmt14/wmt16 datasets."""

    BOS, EOS, UNK = "<s>", "<e>", "<unk>"

    def __init__(self, data_file=None, mode="train", dict_size=-1):
        super().__init__(data_file, mode)
        pairs = []
        with open(self.data_file, errors="ignore") as f:
            for line in f:
                s, _, t = line.rstrip("\n").partition("\t")
                if t:
                    pairs.append((s.split(), t.split()))

        def vocab(side):
            freq = {}
            for p in pairs:
                for w in p[side]:
                    freq[w] = freq.get(w, 0) + 1
            words = [w for w, _ in sorted(freq.items(),
                                          key=lambda x: (-x[1], x[0]))]
            if dict_size > 0:
                words = words[:max(0, dict_size - 3)]
            idx = {self.BOS: 0, self.EOS: 1, self.UNK: 2}
            for w in words:
                if w not in idx:      # corpora may contain literal specials
                    idx[w] = len(idx)
            return idx

        self.src_ids, self.trg_ids = vocab(0), vocab(1)
        su, tu = self.src_ids[self.UNK], self.trg_ids[self.UNK]
        self.data = []
        for s, t in pairs:
            sid = [self.src_ids[self.BOS]] +                 [self.src_ids.get(w, su) for w in s] + [self.src_ids[self.EOS]]
            tid = [self.trg_ids[self.BOS]] + [self.trg_ids.get(w, tu) for w in t]
            tnxt = [self.trg_ids.get(w, tu) for w in t] + [self.trg_ids[self.EOS]]
            self.data.append((sid, tid, tnxt))

    def __getitem__(self, i):
        return tuple(np.array(d) for d in self.data[i])

    def __len__(self):
        return len(self.data)


class WMT14(_ParallelCorpus):
    """reference: paddle.text.datasets.WMT14 (en-fr)."""


class WMT16(_ParallelCorpus):
    """reference: paddle.text.datasets.WMT16 (en-de)."""


class Conll05st(_LocalDataset):
    """reference: paddle.text.datasets.Conll05st (SRL). data_file: a
    column-format file "word<TAB>predicate<TAB>label" with blank lines
    between sentences; yields (word_ids, pred_ids, label_ids) with vocabs
    built from the corpus."""

    def __init__(self, data_file=None, mode="train"):
        super().__init__(data_file, mode)
        sents, cur = [], []
        with open(self.data_file, errors="ignore") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line.strip():
                    if cur:
                        sents.append(cur)
                        cur = []
                    continue
                cur.append(line.split("\t"))
        if cur:
            sents.append(cur)
        self.word_dict, self.predicate_dict, self.label_dict = {}, {}, {}
        for s in sents:
            for w, p, lab in s:
                self.word_dict.setdefault(w, len(self.word_dict))
                self.predicate_dict.setdefault(p, len(self.predicate_dict))
                self.label_dict.setdefault(lab, len(self.label_dict))
        self.data = []
        for s in sents:
            self.data.append((
                np.array([self.word_dict[w] for w, _, _ in s], np.int64),
                np.array([self.predicate_dict[p] for _, p, _ in s], np.int64),
                np.array([self.label_dict[lab] for _, _, lab in s], np.int64)))

    def __getitem__(self, i):
        return self.data[i]

    def __len__(self):
        return len(self.data)


datasets = type("datasets", (), {
    "Imdb": Imdb, "Conll05st": Conll05st, "Movielens": Movielens,
    "UCIHousing": UCIHousing, "WMT14": WMT14, "WMT16": WMT16,
    "Imikolov": Imikolov,
})

from . import strings  # noqa: F401,E402  (StringTensor ops, phi strings analog)
from .strings import StringTensor  # noqa: F401,E402
