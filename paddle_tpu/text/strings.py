"""StringTensor ops — the phi strings kernel family, TPU-native.

Reference (SURVEY §2.1 "PHI fusion/sparse/strings"): paddle/phi/kernels/
strings/ — StringTensor with lower/upper kernels (ASCII + UTF-8 paths,
strings_lower_upper_kernel.h StringLowerKernel/StringUpperKernel) feeding
the tokenizer ops. XLA has no string dtype, so the TPU-native StringTensor
is a host-side numpy unicode array wrapper whose COMPUTE outputs (lengths,
hashes, token ids) are device tensors; the string transforms themselves are
host ops, exactly as the reference keeps them on CPU (string kernels are
CPU-only there too).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor


class StringTensor:
    """Batch of strings with tensor-like shape metadata (reference:
    phi::StringTensor, phi/core/string_tensor.h)."""

    def __init__(self, data, name=None):
        self._data = np.asarray(data, dtype=np.str_)
        self.name = name

    @property
    def shape(self):
        return list(self._data.shape)

    def numpy(self):
        return self._data

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"

    def __eq__(self, other):
        o = other._data if isinstance(other, StringTensor) else np.asarray(other)
        return Tensor(jnp.asarray(self._data == o))


def _as_np(x):
    return x._data if isinstance(x, StringTensor) else np.asarray(x, np.str_)


def lower(x, use_utf8_encoding: bool = True, name=None) -> StringTensor:
    """reference: strings_lower_upper_kernel.h StringLowerKernel — python
    str.lower() is Unicode-aware, covering both the ASCII and utf8 paths."""
    a = _as_np(x)
    if not use_utf8_encoding:
        out = np.char.array(a).lower()  # bytes-style ASCII lowering
        return StringTensor(np.asarray(out, np.str_))
    return StringTensor(np.vectorize(str.lower, otypes=[np.str_])(a)
                        if a.size else a)


def upper(x, use_utf8_encoding: bool = True, name=None) -> StringTensor:
    """reference: StringUpperKernel."""
    a = _as_np(x)
    if not use_utf8_encoding:
        out = np.char.array(a).upper()
        return StringTensor(np.asarray(out, np.str_))
    return StringTensor(np.vectorize(str.upper, otypes=[np.str_])(a)
                        if a.size else a)


def length(x, name=None) -> Tensor:
    """Per-string character count -> int64 device tensor."""
    a = _as_np(x)
    out = np.vectorize(len, otypes=[np.int64])(a) if a.size \
        else np.zeros(a.shape, np.int64)
    return Tensor(jnp.asarray(out))


def strip(x, chars=None, name=None) -> StringTensor:
    a = _as_np(x)
    return StringTensor(np.vectorize(lambda s: s.strip(chars),
                                     otypes=[np.str_])(a) if a.size else a)


def join(x, sep: str = "", axis: int = -1, name=None) -> StringTensor:
    """Concatenate strings along an axis (tokenizer detokenize building
    block). Built row-by-row via an object array — np.apply_along_axis
    would freeze the output dtype at the FIRST row's width and truncate
    longer results."""
    a = _as_np(x)
    moved = np.moveaxis(a, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    joined = np.empty(flat.shape[0], object)
    for i in range(flat.shape[0]):
        joined[i] = sep.join(flat[i].tolist())
    out = np.asarray(joined.reshape(moved.shape[:-1]), np.str_)
    return StringTensor(out)


def to_hash(x, num_buckets: int, name=None) -> Tensor:
    """Stable FNV-1a string hash mod num_buckets -> int64 ids on device
    (the sparse-feature signing step of the CTR pipeline; reference:
    ps feature signing in the data feed)."""
    a = _as_np(x)

    def fnv(s: str) -> int:
        h = 0xcbf29ce484222325
        for byte in s.encode("utf-8"):
            h = ((h ^ byte) * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
        return h % num_buckets

    out = np.vectorize(fnv, otypes=[np.int64])(a) if a.size \
        else np.zeros(a.shape, np.int64)
    return Tensor(jnp.asarray(out))
