"""paddle.quantization analog — QAT fake-quant + PTQ calibration.

Reference (SURVEY §2.3): python/paddle/quantization/ — imperative QAT
(imperative/qat.py ImperativeQuantAware swaps Linear/Conv2D for quantized
twins with FakeQuant layers), PTQ with absmax observers, quanter configs.
TPU-native: fake-quant is a pure jnp round/clip with a straight-through
estimator (identity gradient) expressed as `x + stop_gradient(q(x) - x)` —
no custom C++ fake_quantize kernels (reference:
operators/fake_quantize_op.cu); XLA fuses the quant sim into adjacent ops.
int8 *execution* is not simulated — on TPU the deploy dtype is int8/bf16 via
XLA, and this module produces the scales for that conversion.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..nn.layer import Layer
from .. import nn as _nn


# ------------------------------------------------------------- fake quant
def fake_quant(x, scale, bit_length=8):
    """Symmetric per-tensor fake quantization with STE gradient
    (reference: FakeQuantizeAbsMax, operators/fake_quantize_op.cc)."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def fn(a, s):
        s = jnp.maximum(s, 1e-9)
        q = jnp.clip(jnp.round(a / s * qmax), -qmax, qmax) * s / qmax
        return a + jax.lax.stop_gradient(q - a)  # STE
    return apply_op("fake_quant", fn, [x, scale])


def fake_channel_wise_quant(x, scales, bit_length=8, quant_axis=0):
    """Per-channel weight fake quant (reference:
    FakeChannelWiseQuantizeAbsMax)."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def fn(a, s):
        s = jnp.maximum(s, 1e-9)
        shape = [1] * a.ndim
        shape[quant_axis] = -1
        s = s.reshape(shape)
        q = jnp.clip(jnp.round(a / s * qmax), -qmax, qmax) * s / qmax
        return a + jax.lax.stop_gradient(q - a)
    return apply_op("fake_channel_quant", fn, [x, scales])


def absmax_scale(x, quant_axis: Optional[int] = None):
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if quant_axis is None:
        return jnp.max(jnp.abs(arr))
    axes = tuple(i for i in range(arr.ndim) if i != quant_axis)
    return jnp.max(jnp.abs(arr), axis=axes)


# ------------------------------------------------------------- quanters
class BaseQuanter(Layer):
    def scales(self):
        raise NotImplementedError


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Moving-average absmax activation quanter (reference:
    quantization/quanters/abs_max.py FakeQuanterWithAbsMaxObserver)."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32"):
        super().__init__()
        self._rate = moving_rate
        self._bits = bit_length
        self._scale = None

    def observe(self, x):
        """Update the moving absmax without touching x (PTQ calibration)."""
        cur = absmax_scale(x)
        if self._scale is None:
            self._scale = cur
        else:
            self._scale = self._rate * self._scale + (1 - self._rate) * cur

    def forward(self, x):
        if self.training:
            self.observe(x)
        s = self._scale if self._scale is not None else absmax_scale(x)
        return fake_quant(x, Tensor(s), self._bits)

    def scales(self):
        return Tensor(self._scale) if self._scale is not None else None


class AbsMaxChannelWiseWeightQuanter(BaseQuanter):
    def __init__(self, bit_length=8, quant_axis=1):
        super().__init__()
        self._bits = bit_length
        self._axis = quant_axis
        self._scale = None

    def forward(self, w):
        s = absmax_scale(w, self._axis)
        self._scale = s
        return fake_channel_wise_quant(w, Tensor(s), self._bits, self._axis)

    def scales(self):
        return Tensor(self._scale) if self._scale is not None else None


# ------------------------------------------------------------- config
class QuantConfig:
    """reference: quantization/config.py QuantConfig."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation or FakeQuanterWithAbsMaxObserver
        self.weight = weight or AbsMaxChannelWiseWeightQuanter
        self._type_configs: Dict[type, dict] = {}

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        for t in layer_types:
            self._type_configs[t] = {"activation": activation or self.activation,
                                     "weight": weight or self.weight}

    def _config_for(self, layer):
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        if isinstance(layer, (_nn.Linear, _nn.Conv2D)):
            return {"activation": self.activation, "weight": self.weight}
        return None


# ------------------------------------------------------------- quant layers
class QuantedLayer(Layer):
    """Wraps a Linear/Conv2D: fake-quant activations + weights around the
    original forward (reference: nn/quant wrappers in imperative qat)."""

    def __init__(self, inner, act_quanter, weight_quanter, observe_only=False):
        super().__init__()
        self.inner = inner
        self.act_quanter = act_quanter() if isinstance(act_quanter, type) else act_quanter
        self.weight_quanter = weight_quanter() if isinstance(weight_quanter, type) else weight_quanter
        # PTQ calibration: record activation statistics on the raw values,
        # run the original forward unmodified (reference PTQ observers);
        # QAT (False): simulate quantization in the forward
        self.observe_only = observe_only

    def forward(self, x):
        if self.observe_only:
            if hasattr(self.act_quanter, "observe"):
                self.act_quanter.observe(x)
            return self.inner(x)
        x = self.act_quanter(x)
        w = self.inner.weight
        qw = self.weight_quanter(w)
        orig = w._data
        w._data = qw._data
        try:
            out = self.inner(x)
        finally:
            w._data = orig
        return out


class QAT:
    """Quantization-aware training driver (reference: quantization/qat.py)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace=False) -> Layer:
        return _swap_layers(model, self.config, observe_only=False)

    def convert(self, model: Layer, inplace=False) -> Layer:
        """Fold quanters away for deployment: bake fake-quantized weights."""
        for name, sub in list(model.named_children()):
            if isinstance(sub, QuantedLayer):
                inner = sub.inner
                qw = sub.weight_quanter(inner.weight)
                inner.weight.set_value(qw.detach())
                setattr(model, name, inner)
            else:
                self.convert(sub, inplace=True)
        return model


class PTQ:
    """Post-training quantization: observe absmax during calibration runs,
    then convert (reference: quantization/ptq.py)."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace=False) -> Layer:
        return _swap_layers(model, self.config, observe_only=True)

    def convert(self, model: Layer, inplace=False) -> Layer:
        return QAT(self.config).convert(model)


def _swap_layers(model: Layer, config: QuantConfig, observe_only: bool) -> Layer:
    for name, sub in list(model.named_children()):
        cfg = config._config_for(sub)
        if cfg is not None and not isinstance(sub, QuantedLayer):
            setattr(model, name, QuantedLayer(sub, cfg["activation"],
                                              cfg["weight"],
                                              observe_only=observe_only))
        else:
            _swap_layers(sub, config, observe_only)
    return model


# ------------------------------------------------------- calibration + export
def calibrate(model: Layer, data_loader, num_batches: Optional[int] = None):
    """PTQ calibration pass (reference: quantization/ptq.py — run the
    observer-instrumented model over a calibration DataLoader so the
    activation quanters accumulate moving-absmax statistics).

    `model` must already be PTQ().quantize()'d. Returns the model."""
    was_training = getattr(model, "training", False)
    if hasattr(model, "eval"):
        model.eval()
    from ..core import autograd as _ag
    seen = 0
    with _ag.no_grad():
        for batch in data_loader:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            model(x)
            seen += 1
            if num_batches is not None and seen >= num_batches:
                break
    if was_training and hasattr(model, "train"):
        model.train()
    return model


def _iter_quanted(model: Layer, prefix=""):
    for name, sub in model.named_children():
        full = f"{prefix}.{name}" if prefix else name
        if isinstance(sub, QuantedLayer):
            yield full, sub
        else:
            yield from _iter_quanted(sub, full)


def save_quantized(model: Layer, path: str, input_spec=None):
    """int8-annotated export (reference: PTQ convert + save_inference_model
    with quant attrs; slim's quantized deploy).

    Produces:
      <path>.pdparams / .pdmodel[.json]    — the usual jit.save artifact of
                                             the DEQUANTIZED model (runs
                                             anywhere the fp artifact runs)
      <path>.pdquant.npz                   — per-layer int8 weight codes +
                                             weight/activation scales, the
                                             deploy payload for int8 or
                                             weight-only-int8 serving

    Weight-only int8 is the TPU-relevant deploy mode: int8 codes live in
    HBM (4x smaller), dequantize fuses into the matmul's prologue."""
    import numpy as _np
    from .. import jit as _jit

    payload = {}
    for name, q in _iter_quanted(model):
        w = q.inner.weight
        axis = getattr(q.weight_quanter, "_axis", 1)
        scales = absmax_scale(w, axis)
        s = _np.asarray(scales, _np.float32)
        arr = _np.asarray(w._data, _np.float32)
        shape = [1] * arr.ndim
        shape[axis] = -1
        codes = _np.clip(_np.round(arr / _np.maximum(s.reshape(shape), 1e-9)
                                   * 127.0), -127, 127).astype(_np.int8)
        payload[f"{name}/codes"] = codes
        payload[f"{name}/wscale"] = s
        payload[f"{name}/axis"] = _np.int64(axis)
        act_s = q.act_quanter.scales() if hasattr(q.act_quanter, "scales") \
            else None
        if act_s is not None:
            payload[f"{name}/ascale"] = _np.asarray(act_s._data
                                                    if hasattr(act_s, "_data")
                                                    else act_s, _np.float32)
    _np.savez(path + ".pdquant", **payload)
    # fold the fake-quant into the weights, strip wrappers, export normally
    converted = QAT(QuantConfig()).convert(model)
    _jit.save(converted, path, input_spec=input_spec)
    return path


def load_quantized_weights(path: str):
    """Load the int8 payload: {layer: (codes int8, wscale, axis, ascale?)}."""
    import numpy as _np
    data = _np.load(path + ".pdquant.npz" if not path.endswith(".npz")
                    else path)
    out = {}
    names = {k.rsplit("/", 1)[0] for k in data.files}
    for n in sorted(names):
        out[n] = {
            "codes": data[f"{n}/codes"],
            "wscale": data[f"{n}/wscale"],
            "axis": int(data[f"{n}/axis"]),
            "ascale": data[f"{n}/ascale"] if f"{n}/ascale" in data.files
            else None,
        }
    return out


def dequantize_weights(payload: Dict) -> Dict[str, np.ndarray]:
    """codes * scale / 127 per channel — the server-side weight-only-int8
    dequant (fused into the matmul prologue on TPU)."""
    out = {}
    for n, rec in payload.items():
        shape = [1] * rec["codes"].ndim
        shape[rec["axis"]] = -1
        out[n] = (rec["codes"].astype(np.float32) *
                  rec["wscale"].reshape(shape) / 127.0)
    return out
