"""The eager op surface — paddle.tensor.* semantics lowered to jnp/lax.

TPU-native replacement for the reference's op stack: where the reference
needs a per-backend kernel matrix (paddle/phi/kernels/{cpu,gpu,...} with
KernelKey dispatch, kernel_factory.h:62) plus YAML-generated C++ APIs
(paddle/phi/api/yaml/ops.yaml, api_base.py:1182), a TPU framework needs only
ONE lowering per op — to XLA HLO via jax.numpy/lax — because XLA owns
backend specialization, fusion and layout. Shape/dtype inference (the
reference's infermeta/) is likewise inherited from jax's abstract eval.

Every function here takes/returns `Tensor` and routes through
`tensor.apply_op`, which records the autograd tape. Functions are also
attached as Tensor methods at import (analog of generated
pybind eager_method.cc methods).
"""
from __future__ import annotations

import builtins
import math as _math
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .tensor import Tensor, apply_op, to_tensor
from .dtype import convert_dtype, get_default_dtype
from . import random as _random
from . import autograd


def _arr(x):
    return x._data if isinstance(x, Tensor) else x


def _nodiff(fn, *args, **kw):
    """Run a non-differentiable op without tape recording."""
    from .tensor import _static_record, _no_implicit_f64
    fn = _no_implicit_f64(fn)
    if _static_record is not None:
        res = _static_record(getattr(fn, "__name__", "op"), fn, list(args), kw, None)
        if res is not NotImplemented:
            return res
    out = fn(*[_arr(a) for a in args], **kw)
    if isinstance(out, (tuple, list)):
        return tuple(Tensor(o) for o in out)
    return Tensor(out)


def _floatify(a):
    """Pre-cast integer/bool inputs of float-producing ops to the default
    float dtype so the op never computes in (TPU-emulated) float64; the
    output-side fold in tensor.py stays as the safety net."""
    d = getattr(a, "dtype", None)
    if d is not None and (jnp.issubdtype(d, jnp.integer) or d == jnp.bool_):
        return a.astype(get_default_dtype())
    return a


def _unary(name, fn, float_only=False):
    if float_only:
        inner = fn
        fn = lambda x: inner(_floatify(x))  # noqa: E731
    def op(x, name=None):
        return apply_op(name or op.__name__, fn, [x])
    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = f"Elementwise {name} (reference: paddle.{name}; PHI kernel phi/kernels/*/{name}_kernel)."
    return op


def _binary(name, fn):
    def op(x, y, name=None):
        return apply_op(name or op.__name__, fn, [x, y])
    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = f"Elementwise {name} with broadcasting (reference: paddle.{name})."
    return op


def _cmp(name, fn):
    def op(x, y, name=None):
        return _nodiff(fn, x, y)
    op.__name__ = name
    op.__doc__ = f"Elementwise comparison {name} -> bool tensor (reference: paddle.{name})."
    return op


# ---------------------------------------------------------------- math: unary
exp = _unary("exp", jnp.exp, float_only=True)
expm1 = _unary("expm1", jnp.expm1, float_only=True)
log = _unary("log", jnp.log, float_only=True)
log2 = _unary("log2", jnp.log2, float_only=True)
log10 = _unary("log10", jnp.log10, float_only=True)
log1p = _unary("log1p", jnp.log1p, float_only=True)
sqrt = _unary("sqrt", jnp.sqrt, float_only=True)
rsqrt = _unary("rsqrt", lax.rsqrt, float_only=True)
square = _unary("square", jnp.square)
abs = _unary("abs", jnp.abs)  # noqa: A001
sign = _unary("sign", jnp.sign)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)  # noqa: A001
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
reciprocal = _unary("reciprocal", lambda x: 1.0 / x, float_only=True)
neg = _unary("neg", jnp.negative)
sin = _unary("sin", jnp.sin, float_only=True)
cos = _unary("cos", jnp.cos, float_only=True)
tan = _unary("tan", jnp.tan, float_only=True)
asin = _unary("asin", jnp.arcsin, float_only=True)
acos = _unary("acos", jnp.arccos, float_only=True)
atan = _unary("atan", jnp.arctan, float_only=True)
sinh = _unary("sinh", jnp.sinh, float_only=True)
cosh = _unary("cosh", jnp.cosh, float_only=True)
tanh = _unary("tanh", jnp.tanh, float_only=True)
asinh = _unary("asinh", jnp.arcsinh, float_only=True)
acosh = _unary("acosh", jnp.arccosh, float_only=True)
atanh = _unary("atanh", jnp.arctanh, float_only=True)
sigmoid = _unary("sigmoid", jax.nn.sigmoid, float_only=True)
logsigmoid = _unary("logsigmoid", jax.nn.log_sigmoid, float_only=True)
erf = _unary("erf", lax.erf, float_only=True)
erfinv = _unary("erfinv", lax.erf_inv, float_only=True)
lgamma = _unary("lgamma", lax.lgamma, float_only=True)
digamma = _unary("digamma", lax.digamma, float_only=True)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)

isnan = lambda x, name=None: _nodiff(jnp.isnan, x)
isinf = lambda x, name=None: _nodiff(jnp.isinf, x)
isfinite = lambda x, name=None: _nodiff(jnp.isfinite, x)

# --------------------------------------------------------------- math: binary
add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
def _divide_fn(x, y):
    # Reference parity: paddle.divide keeps INTEGER division (C trunc
    # toward zero, the int DivideFunctor) when both inputs are integer
    # tensors — divide(5, 2) == 2. Only the `/` operator path float-casts
    # (math_op_patch.py _scalar_div_); see _true_divide below.
    xd, yd = jnp.asarray(x).dtype, jnp.asarray(y).dtype
    if jnp.issubdtype(xd, jnp.integer) and jnp.issubdtype(yd, jnp.integer):
        cd = jnp.promote_types(xd, yd)
        xb, yb = jnp.broadcast_arrays(jnp.asarray(x).astype(cd),
                                      jnp.asarray(y).astype(cd))
        return lax.div(xb, yb)
    return jnp.divide(_floatify(x), _floatify(y))


divide = _binary("divide", _divide_fn)
_true_divide = _binary(
    "divide", lambda x, y: jnp.divide(_floatify(x), _floatify(y)))
floor_divide = _binary("floor_divide", jnp.floor_divide)
mod = _binary("mod", jnp.mod)
remainder = mod
pow = _binary("pow", jnp.power)  # noqa: A001
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
hypot = _binary("hypot", jnp.hypot)
logaddexp = _binary("logaddexp", jnp.logaddexp)
heaviside = _binary("heaviside", jnp.heaviside)
nextafter = _binary("nextafter", jnp.nextafter)
copysign = _binary("copysign", jnp.copysign)
inner = _binary("inner", jnp.inner)
outer = _binary("outer", lambda a, b: jnp.outer(a, b))
kron = _binary("kron", jnp.kron)
cross = _binary("cross", jnp.cross)
dot = _binary("dot", jnp.dot)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """Reference: paddle.scale (phi/kernels/*/scale_kernel)."""
    def fn(a):
        out = a * scale + bias if bias_after_scale else (a + bias) * scale
        return out
    return apply_op("scale", fn, [x])


def multiplex(inputs, index, name=None):
    def fn(*args):
        xs, idx = args[:-1], args[-1].reshape(-1)
        stacked = jnp.stack(xs, axis=0)
        return stacked[idx, jnp.arange(stacked.shape[1])]
    return apply_op("multiplex", fn, list(inputs) + [index])


# ---------------------------------------------------------------- reductions
def _reduce(name, fn, float_only=False):
    if float_only:
        inner = fn
        fn = lambda a, **kw: inner(_floatify(a), **kw)  # noqa: E731
    def op(x, axis=None, keepdim=False, name=None):
        if isinstance(axis, (list, tuple)):
            axis = tuple(axis)
        return apply_op(name, lambda a: fn(a, axis=axis, keepdims=keepdim), [x])
    op.__name__ = name
    op.__doc__ = f"Reduction {name} (reference: paddle.{name}; phi/kernels reduce)."
    return op


sum = _reduce("sum", jnp.sum)  # noqa: A001
mean = _reduce("mean", jnp.mean, float_only=True)
prod = _reduce("prod", jnp.prod)
max = _reduce("max", jnp.max)  # noqa: A001
min = _reduce("min", jnp.min)  # noqa: A001
amax = _reduce("amax", jnp.max)
amin = _reduce("amin", jnp.min)
nanmean = _reduce("nanmean", jnp.nanmean, float_only=True)
nansum = _reduce("nansum", jnp.nansum)
logsumexp = _reduce("logsumexp", jax.scipy.special.logsumexp, float_only=True)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return apply_op("std", lambda a: jnp.std(_floatify(a), axis=axis, ddof=ddof, keepdims=keepdim), [x])


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return apply_op("var", lambda a: jnp.var(_floatify(a), axis=axis, ddof=ddof, keepdims=keepdim), [x])


def median(x, axis=None, keepdim=False, name=None):
    return apply_op("median", lambda a: jnp.median(_floatify(a), axis=axis, keepdims=keepdim), [x])


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op("quantile", lambda a: jnp.quantile(_floatify(a), q, axis=axis, keepdims=keepdim), [x])


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return _nodiff(lambda a: jnp.all(a, axis=axis, keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return _nodiff(lambda a: jnp.any(a, axis=axis, keepdims=keepdim), x)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = convert_dtype(dtype)
    return _nodiff(lambda a: jnp.argmax(a, axis=axis, keepdims=keepdim).astype(dt), x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = convert_dtype(dtype)
    return _nodiff(lambda a: jnp.argmin(a, axis=axis, keepdims=keepdim).astype(dt), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return _nodiff(lambda a: jnp.count_nonzero(a, axis=axis, keepdims=keepdim), x)


# --------------------------------------------------------------- scans
def cumsum(x, axis=None, dtype=None, name=None):
    def fn(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.cumsum(a, dtype=convert_dtype(dtype))
        return jnp.cumsum(a, axis=axis, dtype=convert_dtype(dtype))
    return apply_op("cumsum", fn, [x])


def cumprod(x, dim=None, dtype=None, name=None):
    def fn(a):
        if dim is None:
            a = a.reshape(-1)
            return jnp.cumprod(a, dtype=convert_dtype(dtype))
        return jnp.cumprod(a, axis=dim, dtype=convert_dtype(dtype))
    return apply_op("cumprod", fn, [x])


def _cum_extreme(name, better):
    """cummax/cummin (reference: paddle.cummax returning (values, indices)).

    Pairwise associative scan carrying (value, index) so the whole op stays a
    single XLA scan — no serial loop."""
    def op(x, axis=None, dtype="int64", name_=None):
        ax = 0 if axis is None else axis

        def fn(a):
            a2 = a.reshape(-1) if axis is None else a
            ax_ = ax % a2.ndim
            n = a2.shape[ax_]
            iota_shape = [1] * a2.ndim
            iota_shape[ax_] = n
            idx0 = jnp.broadcast_to(
                jnp.arange(n).reshape(iota_shape), a2.shape)

            def comb(l, r):
                lv, li = l
                rv, ri = r
                take_r = better(rv, lv)
                return jnp.where(take_r, rv, lv), jnp.where(take_r, ri, li)

            vals, idxs = lax.associative_scan(comb, (a2, idx0), axis=ax_)
            return vals, idxs.astype(convert_dtype(dtype))

        vals, idxs = apply_op(name, fn, [x], n_outputs=2)
        idxs.stop_gradient = True
        return vals, idxs
    op.__name__ = name
    return op


cummax = _cum_extreme("cummax", lambda r, l: r > l)
cummin = _cum_extreme("cummin", lambda r, l: r < l)


def logcumsumexp(x, axis=None, name=None):
    ax = 0 if axis is None else axis

    def fn(a):
        a2 = a.reshape(-1) if axis is None else a
        return lax.associative_scan(jnp.logaddexp, a2, axis=ax)
    return apply_op("logcumsumexp", fn, [x])


# ------------------------------------------------------------- linear algebra
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """Reference: paddle.matmul (phi/kernels/*/matmul_kernel, MatmulInferMeta
    phi/infermeta/binary.cc). On TPU this maps straight onto the MXU; we set
    preferred_element_type to float32 for low-precision inputs so accumulation
    stays fp32 (the MXU-native contract)."""
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        if a.dtype in (jnp.bfloat16, jnp.float16) and a.dtype == b.dtype:
            # fp32 accumulation on the MXU, output stays in the input dtype
            return jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(a.dtype)
        return jnp.matmul(a, b)
    return apply_op("matmul", fn, [x, y])


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def mv(x, vec, name=None):
    return apply_op("mv", jnp.matmul, [x, vec])


def t(x, name=None):
    def fn(a):
        if a.ndim < 2:
            return a
        return jnp.swapaxes(a, -1, -2)
    return apply_op("t", fn, [x])


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op("addmm", lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), [input, x, y])


def einsum(equation, *operands, name=None):
    """Reference: paddle.einsum (python/paddle/tensor/einsum.py)."""
    return apply_op("einsum", lambda *xs: jnp.einsum(equation, *xs), list(operands))


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def fn(a):
        if p == "fro" and axis is None:
            return jnp.sqrt(jnp.sum(a * a))
        ord_ = p if p != "fro" else "fro"
        if axis is None:
            return jnp.linalg.norm(a.reshape(-1), ord=ord_ if ord_ != "fro" else None, keepdims=keepdim)
        if isinstance(axis, (list, tuple)):
            return jnp.linalg.norm(a, ord=ord_, axis=tuple(axis), keepdims=keepdim)
        return jnp.linalg.norm(a, ord=ord_, axis=axis, keepdims=keepdim)
    return apply_op("norm", fn, [x])


def dist(x, y, p=2.0, name=None):
    return apply_op("dist", lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p), [x, y])


def matrix_power(x, n, name=None):
    return apply_op("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), [x])


def diag(x, offset=0, padding_value=0, name=None):
    def fn(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a), k=offset)
                out = out + (1 - mask) * padding_value
            return out
        return jnp.diagonal(a, offset=offset)
    return apply_op("diag", fn, [x])


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("diagonal", lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2), [x])


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("trace", lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), [x])


def tril(x, diagonal=0, name=None):
    return apply_op("tril", lambda a: jnp.tril(a, k=diagonal), [x])


def triu(x, diagonal=0, name=None):
    return apply_op("triu", lambda a: jnp.triu(a, k=diagonal), [x])


# ------------------------------------------------------------- manipulation
def reshape(x, shape, name=None):
    shape = [int(s) for s in shape]
    return apply_op("reshape", lambda a: jnp.reshape(a, shape), [x])


def reshape_(x, shape, name=None):
    return x._replace(reshape(x, shape))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = list(a.shape[:s]) + [-1] + list(a.shape[e + 1:])
        return jnp.reshape(a, new_shape)
    return apply_op("flatten", fn, [x])


def transpose(x, perm, name=None):
    return apply_op("transpose", lambda a: jnp.transpose(a, axes=perm), [x])


def moveaxis(x, source, destination, name=None):
    return apply_op("moveaxis", lambda a: jnp.moveaxis(a, source, destination), [x])


def swapaxes(x, axis1, axis2, name=None):
    """Reference: paddle.swapaxes(x, axis1, axis2) (tensor/manipulation)."""
    return apply_op("swapaxes", lambda a: jnp.swapaxes(a, axis1, axis2), [x])


def squeeze(x, axis=None, name=None):
    def fn(a):
        if axis is None:
            return jnp.squeeze(a)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
        ax = tuple(a_ for a_ in ax if a.shape[a_] == 1)
        return jnp.squeeze(a, axis=ax) if ax else a
    return apply_op("squeeze", fn, [x])


def unsqueeze(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply_op("unsqueeze", lambda a: jnp.expand_dims(a, ax), [x])


def concat(x, axis=0, name=None):
    tensors = list(x)
    return apply_op("concat", lambda *xs: jnp.concatenate(xs, axis=int(axis)), tensors)


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply_op("stack", lambda *xs: jnp.stack(xs, axis=axis), tensors)


def unstack(x, axis=0, num=None, name=None):
    n = num or x.shape[axis]
    outs = apply_op("unstack", lambda a: tuple(jnp.moveaxis(a, axis, 0)[i] for i in builtins.range(n)),
                    [x], n_outputs=n)
    return list(outs) if isinstance(outs, tuple) else [outs]


def split(x, num_or_sections, axis=0, name=None):
    def fn(a):
        ax = int(axis)
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, axis=ax))
        sections = list(num_or_sections)
        total = a.shape[ax]
        known = builtins.sum(s for s in sections if s != -1)
        sections = [s if s != -1 else total - known for s in sections]
        idx = np.cumsum(sections)[:-1].tolist()
        return tuple(jnp.split(a, idx, axis=ax))
    n = num_or_sections if isinstance(num_or_sections, int) else len(num_or_sections)
    outs = apply_op("split", fn, [x], n_outputs=n)
    return list(outs) if isinstance(outs, tuple) else [outs]


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def tile(x, repeat_times, name=None):
    return apply_op("tile", lambda a: jnp.tile(a, repeat_times), [x])


def expand(x, shape, name=None):
    def fn(a):
        tgt = [a.shape[i - (len(shape) - a.ndim)] if s == -1 else s for i, s in enumerate(shape)]
        return jnp.broadcast_to(a, tgt)
    return apply_op("expand", fn, [x])


def expand_as(x, y, name=None):
    return apply_op("expand_as", lambda a, b: jnp.broadcast_to(a, b.shape), [x, y])


def broadcast_to(x, shape, name=None):
    return apply_op("broadcast_to", lambda a: jnp.broadcast_to(a, shape), [x])


def broadcast_tensors(inputs, name=None):
    outs = apply_op("broadcast_tensors", lambda *xs: tuple(jnp.broadcast_arrays(*xs)),
                    list(inputs), n_outputs=len(inputs))
    return list(outs)


def flip(x, axis, name=None):
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_op("flip", lambda a: jnp.flip(a, axis=tuple(ax)), [x])


def roll(x, shifts, axis=None, name=None):
    return apply_op("roll", lambda a: jnp.roll(a, shifts, axis=axis), [x])


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), [x])


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    """Reference: paddle.nn.functional.pad semantics (phi pad/pad3d kernels)."""
    def fn(a):
        p = list(pad)
        if len(p) == 2 * a.ndim:
            # full-rank pad: first dim -> last dim, (before, after) pairs
            width = [(p[2 * i], p[2 * i + 1]) for i in builtins.range(a.ndim)]
        else:
            # short pad applies to the trailing dims, innermost first:
            # (left, right, top, bottom, ...) i.e. first pair = last dim
            n = len(p) // 2
            trailing = [(p[2 * i], p[2 * i + 1]) for i in builtins.range(n)]
            width = [(0, 0)] * (a.ndim - n) + list(reversed(trailing))
        if mode == "constant":
            return jnp.pad(a, width, mode="constant", constant_values=value)
        jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        return jnp.pad(a, width, mode=jmode)
    return apply_op("pad", fn, [x])


def gather(x, index, axis=0, name=None):
    return apply_op("gather", lambda a, i: jnp.take(a, i, axis=axis),
                    [x, index])


def gather_nd(x, index, name=None):
    def fn(a, i):
        return a[tuple(jnp.moveaxis(i, -1, 0))]
    return apply_op("gather_nd", fn, [x, index])


def take_along_axis(arr, indices, axis, name=None):
    return apply_op("take_along_axis",
                    lambda a, i: jnp.take_along_axis(a, i, axis=axis),
                    [arr, indices])


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):  # noqa: A002
    def fn(a, v, idx):
        v = jnp.broadcast_to(v, idx.shape).astype(a.dtype)
        dims = [jnp.arange(s).reshape([-1 if i == d else 1 for i in builtins.range(idx.ndim)])
                for d, s in enumerate(idx.shape)]
        full_idx = tuple(idx if d == axis else jnp.broadcast_to(dims[d], idx.shape)
                         for d in builtins.range(idx.ndim))
        if reduce == "assign":
            return a.at[full_idx].set(v)
        if reduce == "add":
            return a.at[full_idx].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[full_idx].multiply(v)
        raise ValueError(f"unknown reduce {reduce}")
    return apply_op("put_along_axis", fn, [arr, values, indices])


def scatter(x, index, updates, overwrite=True, name=None):
    """Reference: paddle.scatter (phi scatter kernel) — row-wise scatter."""
    def fn(a, u, i):
        if overwrite:
            return a.at[i].set(u)
        return a.at[i].add(u)
    return apply_op("scatter", fn, [x, updates, index])


def scatter_nd_add(x, index, updates, name=None):
    def fn(a, u, i):
        return a.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply_op("scatter_nd_add", fn, [x, updates, index])


def scatter_nd(index, updates, shape, name=None):
    def fn(u, i):
        z = jnp.zeros(shape, dtype=u.dtype)
        return z.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply_op("scatter_nd", fn, [updates, index])


def index_select(x, index, axis=0, name=None):
    return apply_op("index_select",
                    lambda a, i: jnp.take(a, i, axis=axis), [x, index])


def index_sample(x, index, name=None):
    return apply_op("index_sample",
                    lambda a, i: jnp.take_along_axis(a, i, axis=1),
                    [x, index])


def index_add(x, index, axis, value, name=None):
    def fn(a, v, i):
        a_m = jnp.moveaxis(a, axis, 0)
        v_m = jnp.moveaxis(v, axis, 0)
        out = a_m.at[i].add(v_m)
        return jnp.moveaxis(out, 0, axis)
    return apply_op("index_add", fn, [x, value, index])


def index_put(x, indices, value, accumulate=False, name=None):
    if isinstance(indices, (Tensor, jax.Array, np.ndarray)):
        indices = [indices]
    def fn(a, v, *idxs):
        return a.at[tuple(idxs)].add(v) if accumulate \
            else a.at[tuple(idxs)].set(v)
    return apply_op("index_put", fn, [x, value] + list(indices))


def masked_select(x, mask, name=None):
    m = np.asarray(_arr(mask))  # data-dependent shape: host round-trip, eager only
    def fn(a):
        return a[jnp.asarray(m)]
    return apply_op("masked_select", fn, [x])


def masked_fill(x, mask, value, name=None):
    def fn(a, m, v):
        return jnp.where(m, v.astype(a.dtype) if hasattr(v, "astype") else v, a)
    if isinstance(value, Tensor):
        return apply_op("masked_fill", fn, [x, mask, value])
    return apply_op("masked_fill",
                    lambda a, m: jnp.where(m, value, a), [x, mask])


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op("where", lambda c, a, b: jnp.where(c, a, b),
                    [condition, x, y])


def nonzero(x, as_tuple=False):
    arr = np.asarray(_arr(x))  # data-dependent shape: eager host computation
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i)) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)))


def clip(x, min=None, max=None, name=None):  # noqa: A002
    mn = _arr(min) if isinstance(min, Tensor) else min
    mx = _arr(max) if isinstance(max, Tensor) else max
    return apply_op("clip", lambda a: jnp.clip(a, mn, mx), [x])


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply_op("lerp", lambda a, b, w: a + w * (b - a), [x, y, weight])
    return apply_op("lerp", lambda a, b: a + weight * (b - a), [x, y])


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op("nan_to_num", lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), [x])


def diff(x, n=1, axis=-1, name=None):
    return apply_op("diff", lambda a: jnp.diff(a, n=n, axis=axis), [x])


def repeat_interleave(x, repeats, axis=None, name=None):
    r = _arr(repeats) if isinstance(repeats, Tensor) else repeats
    return apply_op("repeat_interleave", lambda a: jnp.repeat(a, r, axis=axis), [x])


def as_strided(x, shape, stride, offset=0, name=None):
    def fn(a):
        flat = a.reshape(-1)
        idx = offset + builtins.sum(
            np.indices(shape)[i] * stride[i] for i in builtins.range(len(shape)))
        return flat[jnp.asarray(idx.reshape(-1))].reshape(shape)
    return apply_op("as_strided", fn, [x])


def unfold(x, axis, size, step, name=None):
    def fn(a):
        n = (a.shape[axis] - size) // step + 1
        idx = jnp.arange(n)[:, None] * step + jnp.arange(size)[None, :]
        moved = jnp.moveaxis(a, axis, 0)
        out = moved[idx]  # [n, size, ...rest]
        out = jnp.moveaxis(out, (0, 1), (axis, a.ndim))
        return out
    return apply_op("unfold", fn, [x])


# ------------------------------------------------------------------ search
def argsort(x, axis=-1, descending=False, name=None):
    def fn(a):
        idx = jnp.argsort(a, axis=axis)
        return jnp.flip(idx, axis=axis) if descending else idx
    return _nodiff(fn, x)


def sort(x, axis=-1, descending=False, name=None):
    def fn(a):
        s = jnp.sort(a, axis=axis)
        return jnp.flip(s, axis=axis) if descending else s
    return apply_op("sort", fn, [x])


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):  # noqa: A002
    def fn(a):
        ax = axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        src = moved if largest else -moved
        vals, idx = lax.top_k(src, k)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)
    vals, idx = apply_op("topk", fn, [x], n_outputs=2)
    idx.stop_gradient = True
    return vals, idx


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis)
        v = jnp.take(s, k - 1, axis=axis)
        ix = jnp.take(i, k - 1, axis=axis)
        if keepdim:
            v = jnp.expand_dims(v, axis)
            ix = jnp.expand_dims(ix, axis)
        return v, ix
    v, i = apply_op("kthvalue", fn, [x], n_outputs=2)
    i.stop_gradient = True
    return v, i


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along axis + index of its LAST occurrence
    (reference: paddle.mode, phi mode kernel)."""
    def fn(a):
        am = jnp.moveaxis(a, axis, -1)
        s = jnp.sort(am, axis=-1)
        n = s.shape[-1]
        # count of each sorted element = how many equal neighbors
        eq = (s[..., :, None] == s[..., None, :])
        counts = eq.sum(-1)
        best = jnp.argmax(counts, axis=-1)          # first max-count slot
        val = jnp.take_along_axis(s, best[..., None], -1)[..., 0]
        # last occurrence index in the ORIGINAL order
        is_mode = (am == val[..., None])
        pos = jnp.arange(n)
        idx = jnp.max(jnp.where(is_mode, pos, -1), axis=-1)
        if keepdim:
            return (jnp.expand_dims(val, axis), jnp.expand_dims(idx, axis))
        return (val, idx)
    return _nodiff(fn, x)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, name=None):
    arr = np.asarray(_arr(x))  # data-dependent output shape → host, eager only
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    arr = np.asarray(_arr(x))
    if axis is not None:
        raise NotImplementedError
    flat = arr.reshape(-1)
    keep = np.concatenate([[True], flat[1:] != flat[:-1]]) if flat.size else np.array([], bool)
    out = [Tensor(jnp.asarray(flat[keep]))]
    if return_inverse:
        out.append(Tensor(jnp.asarray(np.cumsum(keep) - 1)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, flat.size))
        out.append(Tensor(jnp.asarray(counts)))
    return out[0] if len(out) == 1 else tuple(out)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    dt = jnp.int32 if out_int32 else jnp.int64
    return _nodiff(lambda s, v: jnp.searchsorted(s, v, side=side).astype(dt),
                   sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def bincount(x, weights=None, minlength=0, name=None):
    """Eager: output length = max(x)+1 like the reference. Under jit the
    output SHAPE is value-dependent, so a static bound is required: pass
    minlength >= max(x)+1 (the jnp.bincount `length` contract) — counts
    lower to one scatter-add on device, no host fallback."""
    has_w = weights is not None

    def fn(a, *w):
        import jax.core as _core
        if isinstance(a, _core.Tracer):
            if minlength <= 0:
                raise NotImplementedError(
                    "bincount under jit needs a static output length: pass "
                    "minlength >= max(x)+1 (eager calls size dynamically "
                    "like the reference)")
            length = int(minlength)
        else:
            # builtins.max: plain `max` is this module's reduction op
            length = builtins.max((int(a.max()) + 1) if a.size else 0,
                                  int(minlength))
        return jnp.bincount(a.reshape(-1), weights=w[0] if w else None,
                            length=length)

    return apply_op("bincount", fn, [x] + ([weights] if has_w else []))


def histogram(input, bins=100, min=0, max=0, name=None):  # noqa: A002
    def fn(a):
        lo, hi = ((a.min(), a.max()) if (min == 0 and max == 0)
                  else (jnp.asarray(min, a.dtype), jnp.asarray(max, a.dtype)))
        edges = jnp.linspace(lo, hi, bins + 1)
        h, _ = jnp.histogram(a, bins=edges)
        return h
    return _nodiff(fn, input)


# ------------------------------------------------------------------ logical
equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)


def logical_not(x, name=None):
    return _nodiff(jnp.logical_not, x)


def equal_all(x, y, name=None):
    return _nodiff(lambda a, b: jnp.array_equal(a, b), x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _nodiff(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _nodiff(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def bitwise_and(x, y, name=None):
    return _nodiff(jnp.bitwise_and, x, y)


def bitwise_or(x, y, name=None):
    return _nodiff(jnp.bitwise_or, x, y)


def bitwise_xor(x, y, name=None):
    return _nodiff(jnp.bitwise_xor, x, y)


def bitwise_not(x, name=None):
    return _nodiff(jnp.bitwise_not, x)


def bitwise_left_shift(x, y, name=None):
    return _nodiff(jnp.left_shift, x, y)


def bitwise_right_shift(x, y, name=None):
    return _nodiff(jnp.right_shift, x, y)


# ------------------------------------------------------------------ creation
def _creation_dtype(dtype):
    return convert_dtype(dtype) or get_default_dtype()


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(shape, dtype=_creation_dtype(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(shape, dtype=_creation_dtype(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fv = _arr(fill_value) if isinstance(fill_value, Tensor) else fill_value
    # dtype=None -> float32 (reference: tensor/creation.py full, "if dtype is
    # None: dtype = 'float32'"), never weak-type promotion.
    dt = convert_dtype(dtype) if dtype is not None else get_default_dtype()
    return Tensor(jnp.full(shape, fv, dtype=dt))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(_arr(x), dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(_arr(x), dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(_arr(x), fill_value, dtype=convert_dtype(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start = _arr(start) if isinstance(start, Tensor) else start
    end = _arr(end) if isinstance(end, Tensor) else end
    step = _arr(step) if isinstance(step, Tensor) else step
    dt = convert_dtype(dtype)
    if end is None:
        start, end = 0, start
    if dt is None:
        if builtins.all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dt = convert_dtype("int64")
        else:
            dt = get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(_arr(start) if isinstance(start, Tensor) else start,
                               _arr(stop) if isinstance(stop, Tensor) else stop,
                               int(num), dtype=_creation_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=_creation_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_creation_dtype(dtype)))


def meshgrid(*args, name=None):
    arrs = [_arr(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return [Tensor(m) for m in jnp.meshgrid(*arrs, indexing="ij")]


def tril_indices(row, col, offset=0, dtype="int64", name=None):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def clone(x, name=None):
    return apply_op("clone", lambda a: a + 0, [x])


def assign(x, output=None, name=None):
    t = to_tensor(x) if not isinstance(x, Tensor) else clone(x)
    if output is not None:
        output._replace(t)
        return output
    return t


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=jnp.int64))


def complex(real, imag, name=None):  # noqa: A001
    return apply_op("complex", lambda r, i: lax.complex(r, i), [real, imag])


def as_complex(x, name=None):
    return apply_op("as_complex", lambda a: lax.complex(a[..., 0], a[..., 1]), [x])


def as_real(x, name=None):
    return apply_op("as_real", lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), [x])


# ------------------------------------------------------------------ random
def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(_random.split_key(), tuple(shape),
                                     dtype=_creation_dtype(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(_random.split_key(), tuple(shape),
                                    dtype=_creation_dtype(dtype)))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_random.split_key(), tuple(shape), low, high,
                                     dtype=convert_dtype(dtype)))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(_random.split_key(), n).astype(convert_dtype(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    return Tensor(jax.random.uniform(_random.split_key(), tuple(shape),
                                     dtype=_creation_dtype(dtype), minval=min, maxval=max))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m, s = _arr(mean) if isinstance(mean, Tensor) else mean, _arr(std) if isinstance(std, Tensor) else std
        sh = jnp.broadcast_shapes(np.shape(m), np.shape(s))
        return Tensor(m + s * jax.random.normal(_random.split_key(), sh))
    return Tensor(mean + std * jax.random.normal(_random.split_key(), tuple(shape),
                                                 dtype=get_default_dtype()))


def poisson(x, name=None):
    return Tensor(jax.random.poisson(_random.split_key(), _arr(x)).astype(_arr(x).dtype))


def bernoulli(x, name=None):
    return Tensor(jax.random.bernoulli(_random.split_key(), _arr(x)).astype(_arr(x).dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    logits = jnp.log(jnp.clip(_arr(x), 1e-30, None))
    if replacement:
        out = jax.random.categorical(_random.split_key(), logits, axis=-1,
                                     shape=(*logits.shape[:-1], num_samples))
    else:
        g = jax.random.gumbel(_random.split_key(), logits.shape)
        _, out = lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def rand_like(x, name=None):
    return rand(x.shape, x.dtype)


def randn_like(x, name=None):
    return randn(x.shape, x.dtype)


# ------------------------------------------------------------------ dtype/cast
def cast(x, dtype, name=None):
    dt = convert_dtype(dtype)
    return apply_op("cast", lambda a: a.astype(dt), [x])


def astype(x, dtype):
    return cast(x, dtype)


# ------------------------------------------------------------------ activations (op-level)
def relu(x, name=None):
    return apply_op("relu", jax.nn.relu, [x])


def softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        if dtype is not None:
            a = a.astype(convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return apply_op("softmax", fn, [x])


def log_softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        if dtype is not None:
            a = a.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return apply_op("log_softmax", fn, [x])


# ------------------------------------------------------------------ linalg namespace
class linalg:
    """paddle.linalg analog (reference: python/paddle/tensor/linalg.py);
    lowers to jnp.linalg (XLA custom calls / decompositions on TPU)."""

    @staticmethod
    def svd(x, full_matrices=False, name=None):
        u, s, vh = apply_op("svd", lambda a: jnp.linalg.svd(a, full_matrices=full_matrices),
                            [x], n_outputs=3)
        return u, s, apply_op("conj_t", lambda a: jnp.swapaxes(a, -1, -2), [vh])

    @staticmethod
    def qr(x, mode="reduced", name=None):
        return apply_op("qr", lambda a: jnp.linalg.qr(a, mode=mode), [x], n_outputs=2)

    @staticmethod
    def eig(x, name=None):
        arr = np.asarray(_arr(x))
        w, v = np.linalg.eig(arr)  # CPU-only in XLA; host fallback
        return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))

    @staticmethod
    def eigh(x, UPLO="L", name=None):
        return apply_op("eigh", lambda a: jnp.linalg.eigh(a, symmetrize_input=True), [x], n_outputs=2)

    @staticmethod
    def eigvals(x, name=None):
        arr = np.asarray(_arr(x))
        return Tensor(jnp.asarray(np.linalg.eigvals(arr)))

    @staticmethod
    def eigvalsh(x, UPLO="L", name=None):
        return apply_op("eigvalsh", lambda a: jnp.linalg.eigvalsh(a), [x])

    @staticmethod
    def cholesky(x, upper=False, name=None):
        def fn(a):
            c = jnp.linalg.cholesky(a)
            return jnp.swapaxes(c, -1, -2).conj() if upper else c
        return apply_op("cholesky", fn, [x])

    @staticmethod
    def cholesky_solve(x, y, upper=False, name=None):
        def fn(b, l):
            return jax.scipy.linalg.cho_solve((l, not upper), b)
        return apply_op("cholesky_solve", fn, [x, y])

    @staticmethod
    def inv(x, name=None):
        return apply_op("inv", jnp.linalg.inv, [x])

    @staticmethod
    def pinv(x, rcond=1e-15, hermitian=False, name=None):
        return apply_op("pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), [x])

    @staticmethod
    def det(x, name=None):
        return apply_op("det", jnp.linalg.det, [x])

    @staticmethod
    def slogdet(x, name=None):
        return apply_op("slogdet", lambda a: tuple(jnp.linalg.slogdet(a)), [x], n_outputs=2)

    @staticmethod
    def solve(x, y, name=None):
        return apply_op("solve", jnp.linalg.solve, [x, y])

    @staticmethod
    def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
        def fn(a, b):
            return jax.scipy.linalg.solve_triangular(
                a, b, lower=not upper, trans=1 if transpose else 0,
                unit_diagonal=unitriangular)
        return apply_op("triangular_solve", fn, [x, y])

    @staticmethod
    def lstsq(x, y, rcond=None, driver=None, name=None):
        def fn(a, b):
            sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
            return sol, res, rank, sv
        return apply_op("lstsq", fn, [x, y], n_outputs=4)

    @staticmethod
    def matrix_rank(x, tol=None, hermitian=False, name=None):
        return _nodiff(lambda a: jnp.linalg.matrix_rank(a, rtol=tol), x)

    @staticmethod
    def matrix_power(x, n, name=None):
        return matrix_power(x, n)

    @staticmethod
    def norm(x, p="fro", axis=None, keepdim=False, name=None):
        return norm(x, p=p, axis=axis, keepdim=keepdim)

    @staticmethod
    def cond(x, p=None, name=None):
        return _nodiff(lambda a: jnp.linalg.cond(a, p=p), x)

    @staticmethod
    def multi_dot(tensors, name=None):
        return apply_op("multi_dot", lambda *xs: jnp.linalg.multi_dot(xs), list(tensors))

    @staticmethod
    def lu(x, pivot=True, get_infos=False, name=None):
        def fn(a):
            lu_, piv = jax.scipy.linalg.lu_factor(a)
            return lu_, piv.astype(jnp.int32) + 1  # paddle uses 1-based pivots
        lu_, piv = apply_op("lu", fn, [x], n_outputs=2)
        piv.stop_gradient = True
        if get_infos:
            return lu_, piv, Tensor(jnp.zeros((), jnp.int32))
        return lu_, piv

    @staticmethod
    def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
        """reference: lu_unpack op — split packed LU into P, L, U.
        Batched: pivots are applied as sequential row swaps per batch."""
        def fn(lu_, piv):
            n = lu_.shape[-2]
            L = jnp.tril(lu_, -1) + jnp.eye(n, lu_.shape[-1], dtype=lu_.dtype)
            U = jnp.triu(lu_)
            lead = piv.shape[:-1]
            perm = jnp.broadcast_to(jnp.arange(n), lead + (n,))

            def body(p, i):
                j = piv[..., i].astype(jnp.int32) - 1          # [...] batched
                pi = p[..., i]
                pj = jnp.take_along_axis(p, j[..., None], axis=-1)[..., 0]
                p = p.at[..., i].set(pj)
                oh = jax.nn.one_hot(j, n, dtype=bool)
                p = jnp.where(oh, pi[..., None], p)
                return p, None
            perm, _ = jax.lax.scan(body, perm, jnp.arange(piv.shape[-1]))
            # rows of P: P[perm[r], r] = 1  (swap-applied row order)
            P = jnp.swapaxes(jax.nn.one_hot(perm, n, dtype=lu_.dtype), -1, -2)
            return P, L[..., :, :builtins.min(lu_.shape[-2:])], \
                U[..., :builtins.min(lu_.shape[-2:]), :]
        P, L, U = apply_op("lu_unpack", fn, [x, y], n_outputs=3)
        return P, L, U

    @staticmethod
    def corrcoef(x, rowvar=True, name=None):
        return apply_op("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), [x])

    @staticmethod
    def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
        return apply_op("cov", lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), [x])

    @staticmethod
    def householder_product(x, tau, name=None):
        def fn(a, t):
            m, n = a.shape[-2], a.shape[-1]
            q = jnp.eye(m, dtype=a.dtype)
            q = jnp.broadcast_to(q, (*a.shape[:-2], m, m)).copy() if a.ndim > 2 else q
            for i in builtins.range(n):
                v = jnp.concatenate([jnp.zeros(i, a.dtype), jnp.ones(1, a.dtype), a[..., i + 1:, i]], axis=-1)
                h = jnp.eye(m, dtype=a.dtype) - t[..., i, None, None] * v[..., :, None] * v[..., None, :]
                q = q @ h
            return q[..., :, :n]
        return apply_op("householder_product", fn, [x, tau])


# --------------------------------------------------------------- fft namespace
class fft:
    """paddle.fft analog — lowers to jnp.fft."""
    @staticmethod
    def fft(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op("fft", lambda a: jnp.fft.fft(a, n=n, axis=axis, norm=norm), [x])

    @staticmethod
    def ifft(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op("ifft", lambda a: jnp.fft.ifft(a, n=n, axis=axis, norm=norm), [x])

    @staticmethod
    def rfft(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op("rfft", lambda a: jnp.fft.rfft(a, n=n, axis=axis, norm=norm), [x])

    @staticmethod
    def irfft(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op("irfft", lambda a: jnp.fft.irfft(a, n=n, axis=axis, norm=norm), [x])

    @staticmethod
    def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op("fft2", lambda a: jnp.fft.fft2(a, s=s, axes=axes, norm=norm), [x])

    @staticmethod
    def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op("ifft2", lambda a: jnp.fft.ifft2(a, s=s, axes=axes, norm=norm), [x])

    @staticmethod
    def fftn(x, s=None, axes=None, norm="backward", name=None):
        return apply_op("fftn", lambda a: jnp.fft.fftn(a, s=s, axes=axes, norm=norm), [x])

    @staticmethod
    def ifftn(x, s=None, axes=None, norm="backward", name=None):
        return apply_op("ifftn", lambda a: jnp.fft.ifftn(a, s=s, axes=axes, norm=norm), [x])

    @staticmethod
    def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op("rfft2", lambda a: jnp.fft.rfft2(a, s=s, axes=axes, norm=norm), [x])

    @staticmethod
    def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op("irfft2", lambda a: jnp.fft.irfft2(a, s=s, axes=axes, norm=norm), [x])

    @staticmethod
    def rfftn(x, s=None, axes=None, norm="backward", name=None):
        return apply_op("rfftn", lambda a: jnp.fft.rfftn(a, s=s, axes=axes, norm=norm), [x])

    @staticmethod
    def irfftn(x, s=None, axes=None, norm="backward", name=None):
        return apply_op("irfftn", lambda a: jnp.fft.irfftn(a, s=s, axes=axes, norm=norm), [x])

    @staticmethod
    def hfft(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op("hfft", lambda a: jnp.fft.hfft(a, n=n, axis=axis, norm=norm), [x])

    @staticmethod
    def ihfft(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op("ihfft", lambda a: jnp.fft.ihfft(a, n=n, axis=axis, norm=norm), [x])

    @staticmethod
    def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
        """Hermitian 2-D fft (scipy semantics: forward fft over the leading
        axes FIRST, hermitian fft over the last axis LAST)."""
        def f(a):
            out = jnp.fft.fft(a, n=None if s is None else s[0],
                              axis=axes[0], norm=norm)
            return jnp.fft.hfft(out, n=None if s is None else s[-1],
                                axis=axes[-1], norm=norm)
        return apply_op("hfft2", f, [x])

    @staticmethod
    def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
        """Inverse hermitian 2-D fft: ihfft over the last (real input)
        axis FIRST, then ifft over the leading axes."""
        def f(a):
            out = jnp.fft.ihfft(a, n=None if s is None else s[-1],
                                axis=axes[-1], norm=norm)
            return jnp.fft.ifft(out, n=None if s is None else s[0],
                                axis=axes[0], norm=norm)
        return apply_op("ihfft2", f, [x])

    @staticmethod
    def hfftn(x, s=None, axes=None, norm="backward", name=None):
        def f(a):
            axs = list(range(a.ndim)) if axes is None else list(axes)
            out = a
            for i, ax in enumerate(axs[:-1]):
                out = jnp.fft.fft(out, n=None if s is None else s[i],
                                  axis=ax, norm=norm)
            return jnp.fft.hfft(out, n=None if s is None else s[-1],
                                axis=axs[-1], norm=norm)
        return apply_op("hfftn", f, [x])

    @staticmethod
    def ihfftn(x, s=None, axes=None, norm="backward", name=None):
        def f(a):
            axs = list(range(a.ndim)) if axes is None else list(axes)
            out = jnp.fft.ihfft(a, n=None if s is None else s[-1],
                                axis=axs[-1], norm=norm)
            for i, ax in enumerate(axs[:-1]):
                out = jnp.fft.ifft(out, n=None if s is None else s[i],
                                   axis=ax, norm=norm)
            return out
        return apply_op("ihfftn", f, [x])

    @staticmethod
    def fftshift(x, axes=None, name=None):
        return apply_op("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), [x])

    @staticmethod
    def ifftshift(x, axes=None, name=None):
        return apply_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), [x])

    @staticmethod
    def fftfreq(n, d=1.0, dtype=None, name=None):
        return Tensor(jnp.fft.fftfreq(n, d=d).astype(_creation_dtype(dtype)))

    @staticmethod
    def rfftfreq(n, d=1.0, dtype=None, name=None):
        return Tensor(jnp.fft.rfftfreq(n, d=d).astype(_creation_dtype(dtype)))


# --------------------------------------------------------- indexing on Tensor
def _norm_index(idx):
    if isinstance(idx, Tensor):
        return _arr(idx)
    if isinstance(idx, tuple):
        return tuple(_norm_index(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(np.asarray(idx))
    return idx


def _getitem(self, idx):
    jidx = _norm_index(idx)
    return apply_op("getitem", lambda a: a[jidx], [self])


def _setitem(self, idx, value):
    jidx = _norm_index(idx)
    if isinstance(value, Tensor):
        out = apply_op("setitem", lambda a, v: a.at[jidx].set(v.astype(a.dtype)), [self, value])
    else:
        out = apply_op("setitem", lambda a: a.at[jidx].set(value), [self])
    self._replace(out)


# ------------------------------------------------------------ in-place helpers
def _make_inplace(fn):
    def inplace(self, *args, **kw):
        return self._replace(fn(self, *args, **kw))
    return inplace


def zero_(self):
    self._data = jnp.zeros_like(self._data)
    self._node = None
    return self


def fill_(self, value):
    self._data = jnp.full_like(self._data, value)
    self._node = None
    return self


def uniform_(self, min=-1.0, max=1.0, seed=0):  # noqa: A002
    self._data = jax.random.uniform(_random.split_key(), self._data.shape,
                                    dtype=self._data.dtype, minval=min, maxval=max)
    self._node = None
    return self


def normal_(self, mean=0.0, std=1.0):
    self._data = mean + std * jax.random.normal(_random.split_key(), self._data.shape,
                                                dtype=self._data.dtype)
    self._node = None
    return self


def exponential_(self, lam=1.0):
    u = jax.random.uniform(_random.split_key(), self._data.shape, dtype=self._data.dtype)
    self._data = -jnp.log1p(-u) / lam
    self._node = None
    return self


# ------------------------------------------------------------ method attach
def _attach_methods():
    T = Tensor
    T.__add__ = lambda s, o: add(s, o)
    T.__radd__ = lambda s, o: add(o if isinstance(o, Tensor) else to_tensor(o), s)
    T.__sub__ = lambda s, o: subtract(s, o)
    T.__rsub__ = lambda s, o: subtract(to_tensor(o) if not isinstance(o, Tensor) else o, s)
    T.__mul__ = lambda s, o: multiply(s, o)
    T.__rmul__ = lambda s, o: multiply(to_tensor(o) if not isinstance(o, Tensor) else o, s)
    T.__truediv__ = lambda s, o: _true_divide(s, o)
    T.__rtruediv__ = lambda s, o: _true_divide(to_tensor(o) if not isinstance(o, Tensor) else o, s)
    T.__floordiv__ = lambda s, o: floor_divide(s, o)
    T.__mod__ = lambda s, o: mod(s, o)
    T.__pow__ = lambda s, o: pow(s, o)
    T.__rpow__ = lambda s, o: pow(to_tensor(o) if not isinstance(o, Tensor) else o, s)
    T.__matmul__ = lambda s, o: matmul(s, o)
    T.__rmatmul__ = lambda s, o: matmul(to_tensor(o) if not isinstance(o, Tensor) else o, s)
    T.__neg__ = lambda s: neg(s)
    T.__abs__ = lambda s: abs(s)
    T.__invert__ = lambda s: logical_not(s) if s.dtype == np.dtype(builtins.bool) else bitwise_not(s)
    T.__eq__ = lambda s, o: equal(s, o)
    T.__ne__ = lambda s, o: not_equal(s, o)
    T.__lt__ = lambda s, o: less_than(s, o)
    T.__le__ = lambda s, o: less_equal(s, o)
    T.__gt__ = lambda s, o: greater_than(s, o)
    T.__ge__ = lambda s, o: greater_equal(s, o)
    T.__and__ = lambda s, o: logical_and(s, o) if s.dtype == np.dtype(builtins.bool) else bitwise_and(s, o)
    T.__or__ = lambda s, o: logical_or(s, o) if s.dtype == np.dtype(builtins.bool) else bitwise_or(s, o)
    T.__xor__ = lambda s, o: logical_xor(s, o) if s.dtype == np.dtype(builtins.bool) else bitwise_xor(s, o)
    T.__getitem__ = _getitem
    T.__setitem__ = _setitem

    def _iter(s):
        # Without an explicit __iter__, python's __getitem__ fallback never
        # terminates: jax CLAMPS out-of-range gather indices instead of
        # raising IndexError, so `for row in tensor` would loop forever.
        if s.ndim == 0:
            raise TypeError("iteration over a 0-D tensor")
        return (s[i] for i in builtins.range(s.shape[0]))
    T.__iter__ = _iter

    this = globals()
    method_names = [
        "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "square",
        "abs", "sign", "floor", "ceil", "round", "trunc", "frac", "reciprocal", "neg",
        "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh", "asinh",
        "acosh", "atanh", "sigmoid", "erf", "erfinv", "lgamma", "digamma", "angle",
        "conj", "real", "imag", "isnan", "isinf", "isfinite",
        "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
        "pow", "maximum", "minimum", "fmax", "fmin", "atan2", "hypot", "logaddexp",
        "heaviside", "inner", "outer", "kron", "cross", "dot", "scale",
        "sum", "mean", "prod", "max", "min", "amax", "amin", "nanmean", "nansum",
        "logsumexp", "std", "var", "median", "quantile", "all", "any", "argmax",
        "argmin", "count_nonzero", "cumsum", "cumprod", "logcumsumexp",
        "matmul", "mm", "bmm", "mv", "addmm", "norm", "dist", "matrix_power",
        "diag", "diagonal", "trace", "tril", "triu",
        "reshape", "reshape_", "flatten", "transpose", "moveaxis", "swapaxes",
        "squeeze", "unsqueeze", "split", "chunk", "tile", "expand", "expand_as",
        "broadcast_to", "flip", "roll", "rot90", "pad", "gather", "gather_nd",
        "take_along_axis", "put_along_axis", "scatter", "scatter_nd_add",
        "index_select", "index_sample", "index_add", "index_put", "masked_select",
        "masked_fill", "where", "clip", "lerp", "nan_to_num", "diff",
        "repeat_interleave", "unfold", "argsort", "sort", "topk", "kthvalue",
        "unique", "unique_consecutive", "bincount", "histogram",
        "equal", "not_equal", "greater_than", "greater_equal", "less_than",
        "less_equal", "logical_and", "logical_or", "logical_xor", "logical_not",
        "equal_all", "allclose", "isclose", "bitwise_and", "bitwise_or",
        "bitwise_xor", "bitwise_not", "cast", "astype", "clone", "numel",
        "zeros_like", "ones_like", "relu", "softmax", "log_softmax", "unstack",
        "unbind",
    ]
    for nm in method_names:
        if nm in this:
            setattr(T, nm, this[nm])
    # in-place ops
    T.zero_ = zero_
    T.fill_ = fill_
    T.uniform_ = uniform_
    T.normal_ = normal_
    T.exponential_ = exponential_
    T.add_ = _make_inplace(add)
    T.subtract_ = _make_inplace(subtract)
    T.multiply_ = _make_inplace(multiply)
    T.divide_ = _make_inplace(divide)
    T.scale_ = _make_inplace(scale)
    T.clip_ = _make_inplace(clip)
    T.floor_ = _make_inplace(floor)
    T.ceil_ = _make_inplace(ceil)
    T.exp_ = _make_inplace(exp)
    T.sqrt_ = _make_inplace(sqrt)
    T.rsqrt_ = _make_inplace(rsqrt)
    T.reciprocal_ = _make_inplace(reciprocal)
    T.round_ = _make_inplace(round)
    T.tanh_ = _make_inplace(tanh)
    T.squeeze_ = _make_inplace(squeeze)
    T.unsqueeze_ = _make_inplace(unsqueeze)
    T.flatten_ = _make_inplace(flatten)


def unbind(x, axis=0, name=None):
    return unstack(x, axis=axis)


def increment(x, value=1.0, name=None):
    out = add(x, value)
    x._replace(out)
    return out


_attach_methods()


# --------------------------------------------------------------------------
# Surface-completion batch (reference python/paddle/__init__.py __all__
# parity): math/manipulation stragglers, predicates, and top-level forms of
# the inplace methods.

def add_n(inputs, name=None):
    """reference: paddle.add_n (sum_op) — elementwise sum of a tensor list."""
    if isinstance(inputs, Tensor):
        return clone(inputs)
    def fn(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out
    return apply_op("add_n", fn, list(inputs))


def deg2rad(x, name=None):
    return apply_op("deg2rad", jnp.deg2rad, [x])


def rad2deg(x, name=None):
    return apply_op("rad2deg", jnp.rad2deg, [x])


def diagflat(x, offset=0, name=None):
    return apply_op("diagflat", lambda a: jnp.diagflat(a, k=offset), [x])


def floor_mod(x, y, name=None):
    return mod(x, y)


def frexp(x, name=None):
    return apply_op("frexp", jnp.frexp, [x], n_outputs=2)


def gcd(x, y, name=None):
    return apply_op("gcd", jnp.gcd, [x, y])


def lcm(x, y, name=None):
    return apply_op("lcm", jnp.lcm, [x, y])


def logit(x, eps=None, name=None):
    def fn(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(a) - jnp.log1p(-a)
    return apply_op("logit", fn, [x])


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_op("nanmedian",
                    lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim), [x])


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op("nanquantile",
                    lambda a: jnp.nanquantile(a, q, axis=axis, keepdims=keepdim),
                    [x])


def renorm(x, p, axis, max_norm, name=None):
    """reference: renorm_op — per-slice p-norm clamp along `axis`."""
    def fn(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.linalg.norm(flat.astype(jnp.float32), ord=p, axis=1)
        scale_f = jnp.where(norms > max_norm,
                            max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale_f[:, None].astype(a.dtype)
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)
    return apply_op("renorm", fn, [x])


def sgn(x, name=None):
    def fn(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0.0 + 0.0j, a / jnp.maximum(mag, 1e-38))
        return jnp.sign(a)
    return apply_op("sgn", fn, [x])


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op("stanh",
                    lambda a: scale_b * jnp.tanh(scale_a * a), [x])


def take(x, index, mode="raise", name=None):
    """reference: paddle.take — flat-index gather with raise/wrap/clip."""
    def fn(a, idx):
        flat = a.reshape(-1)
        n = flat.shape[0]
        ii = idx.astype(jnp.int64)
        if mode == "wrap":
            ii = ((ii % n) + n) % n
        else:  # raise/clip both clamp under jit (no python raise in XLA)
            ii = jnp.clip(jnp.where(ii < 0, ii + n, ii), 0, n - 1)
        return flat[ii]
    return apply_op("take", fn, [x, index])


def tensordot(x, y, axes=2, name=None):
    def to_spec(ax):
        if isinstance(ax, Tensor):
            ax = np.asarray(ax._data)
        if isinstance(ax, np.ndarray):
            ax = ax.tolist()
        if isinstance(ax, (list, tuple)) and len(ax) == 2 and all(
                isinstance(a, (list, tuple)) for a in ax):
            return tuple(tuple(a) for a in ax)
        return ax
    spec = to_spec(axes)
    return apply_op("tensordot",
                    lambda a, b: jnp.tensordot(a, b, axes=spec), [x, y])


def vsplit(x, num_or_sections, name=None):
    if x.ndim < 2:
        raise ValueError("vsplit expects ndim >= 2")
    return split(x, num_or_sections, axis=0)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1, name=None):
    """reference: shard_index_op (PS vocab sharding)."""
    if not 0 <= shard_id < nshards:
        raise ValueError(f"shard_id {shard_id} out of range [0, {nshards})")
    size = (index_num + nshards - 1) // nshards
    def fn(a):
        belongs = (a // size) == shard_id
        return jnp.where(belongs, a % size, ignore_value).astype(a.dtype)
    return apply_op("shard_index", fn, [input])


def slice(input, axes, starts, ends, name=None):  # noqa: A001
    """reference: slice_op — python-semantics slice along `axes`."""
    def fn(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = builtins.slice(int(s), int(e))
        return a[tuple(idx)]
    return apply_op("slice", fn, [input])


def strided_slice(x, axes, starts, ends, strides, name=None):
    def fn(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(int(s), int(e), int(st))
        return a[tuple(idx)]
    return apply_op("strided_slice", fn, [x])


def crop(x, shape=None, offsets=None, name=None):
    """reference: crop_tensor_op."""
    def fn(a):
        offs = [0] * a.ndim if offsets is None else [int(o) for o in offsets]
        shp = list(a.shape) if shape is None else [
            a.shape[i] - offs[i] if int(s) == -1 else int(s)
            for i, s in enumerate(shape)]
        idx = tuple(builtins.slice(o, o + s) for o, s in zip(offs, shp))
        return a[idx]
    return apply_op("crop", fn, [x])


def reverse(x, axis, name=None):
    return flip(x, axis)


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    from . import random as _random
    shp = tuple(x.shape)
    dt = convert_dtype(dtype) if dtype is not None else np.dtype(x.dtype)
    out = jax.random.randint(_random.split_key(), shp, int(low), int(high))
    return Tensor(out.astype(dt), stop_gradient=True)


def is_tensor(x):
    return isinstance(x, Tensor)


def is_floating_point(x):
    from .dtype import is_floating_point as _f
    return _f(x.dtype if isinstance(x, Tensor) else x)


def is_integer(x):
    from .dtype import is_integer as _f
    return _f(x.dtype if isinstance(x, Tensor) else x)


def is_complex(x):
    from .dtype import is_complex as _f
    return _f(x.dtype if isinstance(x, Tensor) else x)


def rank(input, name=None):
    return Tensor(jnp.asarray(input.ndim, jnp.int32), stop_gradient=True)


def shape(input, name=None):
    """reference: paddle.shape returns an int Tensor of the shape."""
    return Tensor(jnp.asarray(tuple(input.shape), jnp.int32),
                  stop_gradient=True)


def tolist(x, name=None):
    return np.asarray(x._data).tolist()


# top-level forms of the inplace Tensor methods (reference exports these)
def squeeze_(x, axis=None, name=None):
    return x._replace(squeeze(x, axis))


def unsqueeze_(x, axis, name=None):
    return x._replace(unsqueeze(x, axis))


def tanh_(x, name=None):
    return x._replace(tanh(x))


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._replace(scatter(x, index, updates, overwrite=overwrite))


def _attach_surface_batch():
    T = Tensor
    this = globals()
    for nm in ["add_n", "deg2rad", "rad2deg", "diagflat", "floor_mod",
               "frexp", "gcd", "lcm", "logit", "nanmedian", "nanquantile",
               "renorm", "sgn", "stanh", "take", "tensordot", "vsplit",
               "tolist", "squeeze_", "unsqueeze_", "tanh_", "scatter_"]:
        setattr(T, nm, this[nm])
    T.is_floating_point = lambda s: is_floating_point(s)
    T.is_integer = lambda s: is_integer(s)
    T.is_complex = lambda s: is_complex(s)


_attach_surface_batch()
