"""Eager autograd engine — tape of per-op VJP closures.

TPU-native redesign of the reference's eager autograd
(paddle/fluid/eager/grad_node_info.h:168 GradNodeBase; backward.cc:105
RunBackward). The reference builds an explicit C++ grad-node graph with
dependency counting; here each eager op call captures a `jax.vjp` closure in a
lightweight Node, and `backward()` walks nodes in reverse topological order,
accumulating cotangents per (node, output_index) — the same semantics
(GradTensorHolder accumulation, hooks, partial-graph `paddle.grad`) on a
functional substrate. Under `paddle_tpu.jit` the tape is bypassed entirely:
training steps are pure functions differentiated by jax.grad and compiled by
XLA, which is where performance comes from.
"""
from __future__ import annotations

import contextlib
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

_grad_enabled = [True]


def is_grad_enabled() -> bool:
    return _grad_enabled[0]


def set_grad_enabled(mode: bool):
    _grad_enabled[0] = bool(mode)


class no_grad(contextlib.ContextDecorator):
    """Context manager / decorator disabling tape recording.

    Reference analog: paddle.no_grad (python/paddle/fluid/dygraph/base.py).
    """

    def __enter__(self):
        self._prev = _grad_enabled[0]
        _grad_enabled[0] = False
        return self

    def __exit__(self, *exc):
        _grad_enabled[0] = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _grad_enabled[0]
        _grad_enabled[0] = True
        return self

    def __exit__(self, *exc):
        _grad_enabled[0] = self._prev
        return False


class Node:
    """One recorded op: holds the vjp closure and input tensor refs.

    Mirrors GradNodeBase (grad_node_info.h:168): `inputs` are the edges,
    `out_avals` let us zero-fill cotangents for unused outputs (the
    reference's GradTensorHolder does the same with empty tensors).
    """

    __slots__ = ("name", "vjp_fn", "inputs", "out_avals", "weak_outputs")

    def __init__(self, name: str, vjp_fn: Callable, inputs: Sequence, out_avals: List):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)  # Tensor objects (strong refs keep graph alive)
        self.out_avals = out_avals  # list of jax.ShapeDtypeStruct per output


def _toposort(seed_nodes):
    """Reverse post-order DFS = topological order with consumers first.

    Reference analog: backward.cc:23-64 getInDegreeMap + queue loop; a DFS
    post-order is equivalent for a static tape and needs no counters.
    """
    order, visited = [], set()
    stack = [(n, False) for n in seed_nodes]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            n = t._node
            if n is not None and id(n) not in visited:
                stack.append((n, False))
    order.reverse()  # consumers before producers
    return order


def backward(tensors, grad_tensors=None, retain_graph: bool = False):
    """Run reverse accumulation from `tensors` into leaf `.grad`s.

    Reference analog: egr::Backward (fluid/eager/backward.cc:105).
    """
    from .tensor import Tensor  # cycle-free at call time

    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # cotangent buffers
    node_grads = {}   # id(node) -> list per output index
    leaf_grads = {}   # id(tensor) -> (tensor, array)

    def _seed(t, g):
        if g is None:
            # paddle contract: implicit ones cotangent for ANY shape
            # (varbase_patch_methods.backward seeds ones_like in C++)
            g = jnp.ones_like(t._data)
        else:
            g = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        if t._node is not None:
            bufs = node_grads.setdefault(id(t._node), [None] * len(t._node.out_avals))
            bufs[t._out_idx] = g if bufs[t._out_idx] is None else bufs[t._out_idx] + g
        elif not t.stop_gradient:
            _acc_leaf(t, g)

    def _acc_leaf(t, g):
        ent = leaf_grads.get(id(t))
        leaf_grads[id(t)] = (t, g if ent is None else ent[1] + g)

    seed_nodes = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._node is None:
            raise RuntimeError("backward() called on a tensor with stop_gradient=True "
                               "and no graph")
        _seed(t, g)
        if t._node is not None:
            seed_nodes.append(t._node)

    for node in _toposort(seed_nodes):
        bufs = node_grads.pop(id(node), None)
        if bufs is None:
            continue  # unreachable from seeds
        cts = tuple(
            b if b is not None else jnp.zeros(a.shape, a.dtype)
            for b, a in zip(bufs, node.out_avals)
        )
        in_cts = node.vjp_fn(cts)
        if not retain_graph:
            node.vjp_fn = _freed_vjp
        for t, ct in zip(node.inputs, in_cts):
            if ct is None or t.stop_gradient:
                continue  # user-detached branch: do not flow through
            if t._node is not None:
                nb = node_grads.setdefault(id(t._node), [None] * len(t._node.out_avals))
                i = t._out_idx
                nb[i] = ct if nb[i] is None else nb[i] + ct
            else:
                _acc_leaf(t, ct)

    for t, g in leaf_grads.values():
        for hook in t._hooks:
            out = hook(Tensor(g, stop_gradient=True))
            if out is not None:
                g = out._data if isinstance(out, Tensor) else jnp.asarray(out)
        if t.grad is None:
            t.grad = Tensor(g, stop_gradient=True)
        else:
            t.grad = Tensor(t.grad._data + g, stop_gradient=True)


def _freed_vjp(*_):
    raise RuntimeError(
        "Trying to backward through the graph a second time: the saved "
        "intermediate results were freed. Pass retain_graph=True.")


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph: bool = False, allow_unused: bool = False):
    """paddle.grad analog (reference: autograd/backward_mode.py + GeneralGrad
    in fluid/eager/general_grad.h) — returns grads w.r.t. `inputs` without
    touching `.grad` fields.
    """
    from .tensor import Tensor

    if create_graph:
        raise NotImplementedError(
            "create_graph=True (double grad) is not supported on the eager tape; "
            "use paddle_tpu.jit / jax.grad composition for higher-order AD.")
    single = not isinstance(inputs, (list, tuple))
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if single is False else [inputs]
    if retain_graph is None:
        retain_graph = False

    node_grads, result = {}, {id(t): None for t in inputs}
    wanted = {id(t): t for t in inputs}

    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    seed_nodes = []
    for t, g in zip(outputs, grad_outputs):
        g = (jnp.ones_like(t._data) if g is None
             else (g._data if isinstance(g, Tensor) else jnp.asarray(g)))
        if id(t) in wanted:
            r = result[id(t)]
            result[id(t)] = g if r is None else r + g
        if t._node is not None:
            bufs = node_grads.setdefault(id(t._node), [None] * len(t._node.out_avals))
            bufs[t._out_idx] = g if bufs[t._out_idx] is None else bufs[t._out_idx] + g
            seed_nodes.append(t._node)

    for node in _toposort(seed_nodes):
        bufs = node_grads.pop(id(node), None)
        if bufs is None:
            continue
        cts = tuple(b if b is not None else jnp.zeros(a.shape, a.dtype)
                    for b, a in zip(bufs, node.out_avals))
        in_cts = node.vjp_fn(cts)
        if not retain_graph:
            node.vjp_fn = _freed_vjp
        for t, ct in zip(node.inputs, in_cts):
            if ct is None:
                continue
            if id(t) in wanted:
                r = result[id(t)]
                result[id(t)] = ct if r is None else r + ct
            if t._node is not None and not t.stop_gradient:
                nb = node_grads.setdefault(id(t._node), [None] * len(t._node.out_avals))
                i = t._out_idx
                nb[i] = ct if nb[i] is None else nb[i] + ct
            elif t._node is not None:
                # still propagate through intermediates regardless of flag:
                # intermediates produced under grad mode have stop_gradient
                # False by construction; a True here means a detached branch.
                pass

    grads = []
    for t in inputs:
        g = result[id(t)]
        if g is None and not allow_unused:
            raise RuntimeError(
                "One of the differentiated tensors appears to not have been "
                "used in the graph. Set allow_unused=True if this is desired.")
        grads.append(None if g is None else Tensor(g, stop_gradient=True))
    return grads[0] if single else grads
