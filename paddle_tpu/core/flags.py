"""Global flag registry — paddle.set_flags/get_flags analog.

Reference (SURVEY §5.6): gflags exported through PADDLE_DEFINE_EXPORTED_*
(phi/core/flags.h:43-95, 89 flags in phi/core/flags.cc), readable/settable
from Python via paddle.set_flags / FLAGS_* env. Here one typed registry —
the reference's dual fluid/phi registries collapse (SURVEY §5.6 explicitly
calls for that). Flags that map to XLA/jax controls apply them on set.

NaN/Inf checking (SURVEY §5.2): FLAGS_check_nan_inf scans every op output on
the eager path (reference: eager/nan_inf_utils.cc per-op output scans) and
raises with the op name — on the jit path, use jax's debug_nans which this
flag also toggles.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_REGISTRY: Dict[str, Any] = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_benchmark": False,
    "FLAGS_cudnn_deterministic": True,   # TPU: deterministic by construction
    "FLAGS_use_autotune": True,          # XLA autotuning on by default
    # measured Pallas tile selection (flash bq/bk) with a persistent cache;
    # opt-in like the reference's conv autotune (switch_autotune.cc) since
    # each candidate costs a compile at first encounter of a shape
    "FLAGS_flash_autotune": False,
    # channels-last vision fast path: convs compute with TPU-preferred
    # NHWC/HWIO dimension numbers even when the API-level layout is NCHW,
    # and layout-aware models (resnet/swin) run their conv trunk internally
    # channels-last with transposes only at trunk entry/exit
    "FLAGS_conv_channels_last": False,
    "FLAGS_allocator_strategy": "xla",   # no custom allocator on TPU
    "FLAGS_fraction_of_gpu_memory_to_use": 0.0,
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_max_inplace_grad_add": 0,
    "FLAGS_embedding_deterministic": 1,
    "FLAGS_sync_nccl_allreduce": False,  # XLA collectives are ordered
    "FLAGS_stop_check_timeout": 300,
}

# fast-path mirror consumed by apply_op (bool lookup, no dict churn)
check_nan_inf: bool = False
benchmark: bool = False
conv_channels_last: bool = False


def _apply_side_effects(name: str, value):
    global check_nan_inf, benchmark, conv_channels_last
    if name == "FLAGS_conv_channels_last":
        conv_channels_last = (bool(int(value))
                              if not isinstance(value, bool) else value)
    elif name == "FLAGS_check_nan_inf":
        check_nan_inf = bool(int(value)) if not isinstance(value, bool) else value
        try:
            import jax
            jax.config.update("jax_debug_nans", check_nan_inf)
        except Exception:
            pass
    elif name == "FLAGS_benchmark":
        benchmark = bool(int(value)) if not isinstance(value, bool) else value


def set_flags(flags: Dict[str, Any]):
    """reference: paddle.set_flags (pybind global_value_getter_setter.cc)."""
    for name, value in flags.items():
        # unknown names accepted for fwd-compat (env vars behave the same)
        _REGISTRY[name] = value
        _apply_side_effects(name, value)


def get_flags(flags) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    return {name: _REGISTRY.get(name) for name in flags}


def _init_from_env():
    for key, val in os.environ.items():
        if key.startswith("FLAGS_"):
            cur = _REGISTRY.get(key)
            if isinstance(cur, bool):
                parsed = val.lower() in ("1", "true", "yes")
            elif isinstance(cur, int):
                parsed = int(val)
            elif isinstance(cur, float):
                parsed = float(val)
            else:
                parsed = val
            _REGISTRY[key] = parsed
            _apply_side_effects(key, parsed)


_init_from_env()
