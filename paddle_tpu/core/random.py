"""Global RNG state.

The reference carries per-device Generator state (paddle/phi/core/generator.h)
and exposes `paddle.seed`. On TPU the idiomatic substrate is JAX's splittable
threefry keys: we keep one global key for the eager path and split on every
draw; jitted/functional paths take explicit keys (see nn.Layer functional
apply and distributed.random RNG trackers for TP-determinism, mirroring the
reference's mpu/random.py tracker semantics).
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()
_DEFAULT_SEED = 0
_prng_picked = False


def _auto_prng_impl():
    """On TPU-class backends default the key impl to 'rbg' (hardware RNG).

    Measured v5e (r5): bert-base MLM with hidden+attention dropout runs
    the threefry bitstream in XLA at ~31 ms of a 135 ms step; rbg cuts the
    step to 117 ms (44.2% -> 51.0% MFU) with identical distributions.
    Respected overrides: JAX_DEFAULT_PRNG_IMPL env or an explicit
    jax.config.update before first draw. CPU/GPU keep threefry (test
    determinism across hosts)."""
    global _prng_picked
    if _prng_picked:
        return
    _prng_picked = True
    import os
    if os.environ.get("JAX_DEFAULT_PRNG_IMPL"):
        return
    if str(jax.config.jax_default_prng_impl) != "threefry2x32":
        return   # user already picked an impl via jax.config.update
    try:
        plat = jax.default_backend()
    except Exception:
        return
    if plat in ("tpu", "axon"):
        jax.config.update("jax_default_prng_impl", "rbg")


def _get():
    if not hasattr(_state, "key"):
        _auto_prng_impl()
        _state.key = jax.random.key(_DEFAULT_SEED)
    return _state.key


def seed(s: int):
    """Reset the global RNG (reference: paddle.seed, framework/random.py)."""
    _auto_prng_impl()
    _state.key = jax.random.key(int(s))
    return _state.key


def get_state():
    return _get()


def set_state(key):
    _state.key = key


def key_state_dict() -> dict:
    """Serializable snapshot of the global eager RNG stream — raw key bits
    + impl name, the resilience.TrainState "rng" slot. Restoring it makes
    every post-resume draw (dropout masks, sampling) continue the exact
    stream the interrupted run would have produced (bit-exact resume needs
    the key, not the seed: the key has advanced past seed() by one split
    per draw)."""
    import numpy as np
    key = _get()
    return {"data": np.asarray(jax.random.key_data(key)),
            "impl": str(jax.random.key_impl(key))}


def set_key_state_dict(state: dict):
    import jax.numpy as jnp
    data = jnp.asarray(state["data"])
    impl = state.get("impl")
    _state.key = jax.random.wrap_key_data(data, impl=impl) if impl \
        else jax.random.wrap_key_data(data)
    return _state.key


class trace_key_scope:
    """Bind randomness to an explicit key while tracing a jitted function.

    Inside `paddle_tpu.jit` traces, drawing from the global eager key would
    bake the randomness in as a compile-time constant (same dropout mask every
    step). The jit layer wraps traces in this scope with a per-step key input;
    `split_key()` then derives subkeys from it, so randomness is a proper
    traced input. Analog of the reference's seed plumbing into dropout kernels
    (phi dropout kernels take a seed tensor) and the mpu RNG trackers.
    """

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        stack = getattr(_state, "trace_stack", None)
        if stack is None:
            stack = _state.trace_stack = []
        stack.append([self._key])
        return self

    def __exit__(self, *exc):
        _state.trace_stack.pop()
        return False


def in_trace_scope() -> bool:
    stack = getattr(_state, "trace_stack", None)
    return bool(stack)


def _original_split_key():
    key, sub = jax.random.split(_get())
    _state.key = key
    return sub


# installed by paddle_tpu.static: returns a symbolic per-run key Variable
# while a static Program is recording, else None
_op_key_hook = None


def op_key():
    """Key for randomness *inside op implementations* that thread the key
    through apply_op as an input (dropout et al). In static graph mode this
    yields a symbolic key Variable fed fresh by the Executor every run — the
    analog of the reference plumbing a seed tensor into dropout kernels — so
    recorded programs don't freeze their masks at build time."""
    if _op_key_hook is not None:
        k = _op_key_hook()
        if k is not None:
            return k
    return split_key()


def split_key():
    """Return a fresh subkey — from the trace scope if active, else the
    global eager stream."""
    stack = getattr(_state, "trace_stack", None)
    if stack:
        cell = stack[-1]
        key, sub = jax.random.split(cell[0])
        cell[0] = key
        return sub
    return _original_split_key()
