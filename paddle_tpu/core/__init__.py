from . import dtype, random, autograd, tensor, ops  # noqa: F401
from .tensor import Tensor, Parameter, to_tensor, apply_op  # noqa: F401
from .autograd import no_grad, enable_grad, grad, backward, is_grad_enabled, set_grad_enabled  # noqa: F401
