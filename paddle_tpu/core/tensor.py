"""Eager Tensor — a thin autograd-aware wrapper over jax.Array.

TPU-native redesign of the reference's eager Tensor
(paddle/fluid/eager/ + pybind/eager.cc:1246 Tensor type, eager_method.cc
methods). The reference couples a C++ DenseTensor with AutogradMeta; here the
storage IS a jax.Array (device-resident, XLA-managed — no custom allocator:
the StreamSafeCUDAAllocator concern of
paddle/fluid/memory/allocation/stream_safe_cuda_allocator.h:61 does not exist
on TPU, where XLA owns buffers and ordering), and autograd metadata is the
(`_node`, `_out_idx`, `stop_gradient`, `grad`) quadruple consumed by
core.autograd.

`apply_op` is the single entry point every eager op goes through — the analog
of the generated `*_ad_func` forward functions (eager_gen.py:192): run the
forward, and iff grad is enabled and some input requires grad, capture a
jax.vjp closure on the tape.
"""
from __future__ import annotations

import builtins

import numpy as np
import jax
import jax.numpy as jnp

from . import autograd
from . import flags
from .dtype import convert_dtype, get_default_dtype, is_floating_point


def _scan_nan_inf(name, outs):
    """FLAGS_check_nan_inf eager scan (reference: eager/nan_inf_utils.cc
    CheckTensorHasNanOrInf called from generated forwards). Tracer-safe: the
    check is skipped inside jit traces, where jax_debug_nans covers it."""
    for o in outs:
        if isinstance(o, jax.core.Tracer) or not jnp.issubdtype(
                o.dtype, jnp.floating):
            continue
        if bool(jnp.any(~jnp.isfinite(o))):
            raise FloatingPointError(
                f"Operator {name} output contains NaN/Inf "
                f"(FLAGS_check_nan_inf is set)")

_PRINT_OPTS = {"precision": 8, "threshold": 1000, "edgeitems": 3, "linewidth": 80}

# installed by paddle_tpu.analysis.transfer (transfer_guard): called with
# (kind, raw data) before every host-interop read so an implicit transfer
# on a TRACER-backed Tensor raises a named error instead of jax's
# anonymous concretization failure. None (the default) costs one check.
_concretization_hook = None


def _note_host_read(kind, data):
    if _concretization_hook is not None:
        _concretization_hook(kind, data)


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "_node", "_out_idx", "name",
                 "persistable", "_hooks", "pspec", "_layout", "__weakref__")

    def __init__(self, data, stop_gradient: bool = True, name: str = None):
        if isinstance(data, Tensor):
            data = data._data
        elif not isinstance(data, jax.Array):
            data = jnp.asarray(data)
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_idx = 0
        self.name = name
        self.persistable = False
        self._hooks = []
        self.pspec = None  # optional jax PartitionSpec annotation (distributed)
        # internal physical-layout annotation ("NHWC" while riding the
        # channels-last conv trunk; see nn.layout). None = API layout.
        self._layout = None

    # ---- basic properties -------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def size(self):
        return int(self._data.size)

    @property
    def T(self):
        from . import ops
        return ops.t(self)

    @property
    def place(self):
        d = self._data.devices()
        return next(iter(d)) if d else None

    def numel(self):
        return self.size

    def is_floating_point(self):
        return is_floating_point(self.dtype)

    # ---- host interop -----------------------------------------------------
    # every entry point notifies the analysis concretization hook first:
    # under analysis.transfer_guard a tracer-backed read raises a named
    # HostTransferError (layer path + kind) instead of jax's anonymous
    # concretization failure
    def numpy(self):
        _note_host_read("numpy", self._data)
        return np.asarray(self._data)

    def item(self, *args):
        _note_host_read("item", self._data)
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        _note_host_read("tolist", self._data)
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        _note_host_read("asarray", self._data)
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        _note_host_read("float", self._data)
        return float(self.item())

    def __int__(self):
        _note_host_read("int", self._data)
        return int(self.item())

    def __bool__(self):
        _note_host_read("bool", self._data)
        if self.size != 1:
            raise ValueError("The truth value of a multi-element Tensor is ambiguous")
        return bool(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._data.shape[0]

    def __hash__(self):
        return id(self)

    def __repr__(self):
        with np.printoptions(**{k: v for k, v in _PRINT_OPTS.items() if k != "linewidth"}):
            body = str(self.numpy())
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
                f"stop_gradient={self.stop_gradient},\n       {body})")

    # ---- autograd surface -------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._data), stop_gradient=True)
        else:
            self.grad = None

    def detach(self) -> "Tensor":
        return Tensor(self._data, stop_gradient=True, name=self.name)

    def detach_(self) -> "Tensor":
        self._node = None
        self.stop_gradient = True
        return self

    def register_hook(self, hook):
        """Reference analog: Tensor.register_hook (varbase_patch_methods.py)."""
        self._hooks.append(hook)

        class _Remover:
            def remove(_self):
                try:
                    self._hooks.remove(hook)
                except ValueError:
                    pass
        return _Remover()

    @property
    def is_leaf(self):
        return self._node is None

    # ---- in-place-style mutation (functional under the hood) --------------
    def _replace(self, new: "Tensor"):
        """Adopt another tensor's value+graph in place.

        XLA is functional, so the reference's true in-place ops
        (ops.yaml `inplace` annotations) are emulated by rebinding this
        python object to the functionally-updated array while keeping the
        autograd edge — same user-visible semantics, no aliasing.
        """
        self._data = new._data
        self._node = new._node
        self._out_idx = new._out_idx
        self.stop_gradient = new.stop_gradient
        self._layout = getattr(new, "_layout", None)
        return self

    def set_value(self, value):
        value = value._data if isinstance(value, Tensor) else jnp.asarray(value, dtype=self._data.dtype)
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(f"set_value shape mismatch {value.shape} vs {self._data.shape}")
        self._data = value
        self._node = None
        return self

    def copy_(self, other):
        return self.set_value(other)

    # NOTE: arithmetic dunders, indexing, and the ~200 tensor methods are
    # attached by core.ops at import time (single source of truth for the op
    # surface — the analog of the generated pybind methods in
    # eager_op_function.cc).


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _wrap_scalar(x, like=None):
    """Convert python scalars / numpy to jnp for vjp-traced args."""
    return jnp.asarray(x)


_amp_cast = None  # installed lazily by paddle_tpu.amp to avoid an import cycle
# installed by paddle_tpu.static: diverts op dispatch into Program recording
# when static mode is on and an input is a static Variable (returns
# NotImplemented to fall through to eager execution)
_static_record = None


def _install_amp_hook():
    global _amp_cast
    from ..amp.auto_cast import amp_cast_inputs
    _amp_cast = amp_cast_inputs


# float64 is opt-in (MIGRATION.md "Integer dtypes"): with x64 enabled for
# real int64 semantics, ops like divide/mean/sin would promote integer
# inputs to float64 — slow software emulation on TPU and a dtype surprise.
# Policy: unless an input already IS 64-bit inexact (user opted in) or the
# op is an explicit cast, 64-bit inexact outputs fold back to 32-bit.
_F64_OPT_IN_OPS = frozenset({"cast", "astype"})
_F64 = np.dtype("float64")
_C128 = np.dtype("complex128")


def _no_implicit_f64(fn):
    import functools

    @functools.wraps(fn)
    def wrapped(*xs, **kw):
        out = fn(*xs, **kw)
        if builtins.any(getattr(x, "dtype", None) in (_F64, _C128) for x in xs):
            return out

        def fix(o):
            d = getattr(o, "dtype", None)
            if d == _F64:
                return o.astype(jnp.float32)
            if d == _C128:
                return o.astype(jnp.complex64)
            return o

        if isinstance(out, (tuple, list)):
            fixed = [fix(o) for o in out]
            if hasattr(out, "_fields"):        # namedtuple (e.g. SVDResult)
                return type(out)(*fixed)
            return type(out)(fixed)
        return fix(out)
    return wrapped


def apply_op(name, fn, tensor_args, static_kwargs=None, n_outputs=None):
    """Run `fn(*arrays, **static_kwargs)` eagerly, recording a tape node.

    - `tensor_args`: positional inputs that participate in differentiation
      (Tensors or array-likes; non-Tensors are treated as constants).
    - `static_kwargs`: non-differentiable config closed over the vjp.
    Returns Tensor or tuple of Tensors matching fn's output structure.

    Reference analog: the eager_gen.py:192 FORWARD_FUNCTION_TEMPLATE body
    (minus AMP/layout autotune, which live in paddle_tpu.amp as dtype
    policies instead of per-op rewrite).
    """
    static_kwargs = static_kwargs or {}
    if name not in _F64_OPT_IN_OPS:
        fn = _no_implicit_f64(fn)
    if _static_record is not None:
        res = _static_record(name, fn, tensor_args, static_kwargs, n_outputs)
        if res is not NotImplemented:
            return res
    arrays = []
    diff_mask = []
    for a in tensor_args:
        if isinstance(a, Tensor):
            arrays.append(a._data)
            diff_mask.append(not a.stop_gradient or a._node is not None)
        else:
            arrays.append(a if isinstance(a, jax.Array) else jnp.asarray(a))
            diff_mask.append(False)

    if _amp_cast is not None:
        arrays = _amp_cast(name, arrays)

    record = autograd.is_grad_enabled() and any(diff_mask)

    if not record:
        out = fn(*arrays, **static_kwargs)
        multi = isinstance(out, (tuple, list))
        outs = tuple(out) if multi else (out,)
        if flags.check_nan_inf:
            _scan_nan_inf(name, outs)
        ts = tuple(Tensor(o, stop_gradient=True) for o in outs)
        return ts if multi else ts[0]

    def pure(*xs):
        res = fn(*xs, **static_kwargs)
        return tuple(res) if isinstance(res, (tuple, list)) else (res,)

    outs, vjp_fn = jax.vjp(pure, *arrays)
    if flags.check_nan_inf:
        _scan_nan_inf(name, outs)
    multi_out = n_outputs is not None or len(outs) > 1
    avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs]

    in_tensors = [a for a in tensor_args if isinstance(a, Tensor)]
    t_idx = [i for i, a in enumerate(tensor_args) if isinstance(a, Tensor)]

    def node_vjp(cts, _vjp=vjp_fn, _t_idx=tuple(t_idx), _n=len(arrays)):
        full = _vjp(cts)
        return [full[i] for i in _t_idx]

    node = autograd.Node(name, node_vjp, in_tensors, avals)
    results = []
    for i, o in enumerate(outs):
        t = Tensor(o, stop_gradient=False)
        t._node = node
        t._out_idx = i
        results.append(t)
    # fn may genuinely return a 1-tuple; treat len>1 or explicit n_outputs as multi
    if len(results) == 1 and n_outputs is None:
        return results[0]
    return tuple(results)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor analog (reference: python/paddle/tensor/creation.py)."""
    del place  # single logical device space; sharding handles placement
    if isinstance(data, Tensor):
        arr = data._data
    elif isinstance(data, jax.Array):
        arr = data
    else:
        arr = np.asarray(data)
        # 64-bit inexact stays opt-in (MIGRATION.md): python/numpy float
        # and complex default to their 32-bit paddle defaults unless the
        # caller passes dtype= explicitly
        if dtype is None:
            if arr.dtype == np.float64:
                arr = arr.astype(get_default_dtype())
            elif arr.dtype == np.complex128:
                arr = arr.astype(np.complex64)
    dt = convert_dtype(dtype)
    arr = jnp.asarray(arr, dtype=dt) if dt is not None else jnp.asarray(arr)
    return Tensor(arr, stop_gradient=stop_gradient)


class Parameter(Tensor):
    """Trainable tensor (reference: fluid/framework.py Parameter — a Variable
    with trainable=True; here simply stop_gradient=False + persistable)."""

    def __init__(self, data, trainable: bool = True, name: str = None):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.persistable = True

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v
