"""Dtype system.

TPU-native reimagining of the reference's dtype surface
(reference: paddle/phi/common/data_type.h — DataType enum; python/paddle
`paddle.float32` etc.). We expose paddle-style dtype names backed directly by
numpy/jax dtypes: there is no separate enum because JAX arrays carry numpy
dtypes natively and XLA handles layout.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

# Canonical dtype objects (numpy dtype instances).
float16 = np.dtype("float16")
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float32 = np.dtype("float32")
float64 = np.dtype("float64")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
uint8 = np.dtype("uint8")
uint16 = np.dtype("uint16")
uint32 = np.dtype("uint32")
uint64 = np.dtype("uint64")
bool_ = np.dtype("bool")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")
float8_e4m3fn = np.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)

_ALIASES = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "uint16": uint16, "uint32": uint32, "uint64": uint64,
    "bool": bool_,
    "complex64": complex64, "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn, "float8_e5m2": float8_e5m2,
}

FLOATING = {float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2}
INTEGER = {int8, int16, int32, int64, uint8, uint16, uint32, uint64}
COMPLEX = {complex64, complex128}


def convert_dtype(dtype) -> np.dtype:
    """Normalize any user-supplied dtype spec to a numpy dtype.

    Accepts strings ("float32", "bf16"), numpy dtypes, jnp dtypes, python
    types (float/int/bool), and Tensor.dtype values.
    """
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower()
        if key in _ALIASES:
            return _ALIASES[key]
        raise ValueError(f"Unknown dtype string: {dtype!r}")
    if dtype is float:
        return float32
    if dtype is int:
        return int64
    if dtype is bool:
        return bool_
    try:
        return np.dtype(dtype)
    except TypeError:
        raise ValueError(f"Cannot convert {dtype!r} to a dtype") from None


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in FLOATING


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in INTEGER


def is_complex(dtype) -> bool:
    return convert_dtype(dtype) in COMPLEX


_DEFAULT_DTYPE = [float32]


def set_default_dtype(dtype):
    """paddle.set_default_dtype analog (reference: python/paddle/framework/framework.py)."""
    d = convert_dtype(dtype)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(f"default dtype must be floating, got {d}")
    _DEFAULT_DTYPE[0] = d


def get_default_dtype() -> np.dtype:
    return _DEFAULT_DTYPE[0]
