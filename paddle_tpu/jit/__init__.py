"""paddle.jit analog (reference: python/paddle/jit/) — to_static over XLA."""
from .api import to_static, not_to_static, StaticFunction, InputSpec, ignore_module  # noqa: F401
from . import dy2static  # noqa: F401
from .train_step import TrainStep  # noqa: F401
from .save_load import save, load, TranslatedLayer  # noqa: F401
