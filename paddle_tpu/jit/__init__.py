"""paddle.jit analog (reference: python/paddle/jit/) — to_static over XLA."""
from .api import to_static, not_to_static, StaticFunction, InputSpec, ignore_module  # noqa: F401
from . import dy2static  # noqa: F401
from .train_step import TrainStep  # noqa: F401
from .save_load import save, load, TranslatedLayer  # noqa: F401

_to_static_enabled = True
_code_level = 0
_verbosity = 0


def enable_to_static(enable: bool = True):
    """reference: jit.enable_to_static — global on/off switch; StaticFunction
    falls through to eager when disabled."""
    global _to_static_enabled
    _to_static_enabled = bool(enable)


def set_code_level(level=100, also_to_stdout=False):
    """reference: dy2static debug — level>0 prints transformed code."""
    global _code_level
    _code_level = level


def set_verbosity(level=0, also_to_stdout=False):
    global _verbosity
    _verbosity = level
