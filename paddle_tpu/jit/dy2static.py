"""dy2static — AST rewrite of Python control flow for @to_static.

Reference: python/paddle/jit/dy2static/ (ast_transformer.py pipeline:
IfElseTransformer, LoopTransformer, LogicalTransformer, …) rewrites user
Python into convert_* runtime calls so data-dependent `if`/`while` become
graph ops (conditional_block / while ops executed by InterpreterCore,
call stack SURVEY §3.4).

TPU-native: the same source rewrite, but the convert_* runtime dispatches
on the predicate at trace time —
  * concrete (eager, or shape-static under trace): plain Python control flow;
  * a traced jax tracer: `lax.cond` / `lax.while_loop`, keeping the whole
    function ONE compiled XLA program with structured control flow instead
    of trace-time unrolling or a Python-side interpreter loop.

Only the control-flow subset that is data-dependent needs rewriting; all
other Python executes natively under the jax trace (closures, calls,
containers), so the transformer is deliberately small: If / While /
For-over-range / BoolOp(and,or) / UnaryOp(not) / ternary IfExp.
"""
from __future__ import annotations

import ast
import functools
import inspect
import re
import textwrap
import types
from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    "convert_ifelse", "convert_while_loop", "convert_logical_and",
    "convert_logical_or", "convert_logical_not", "convert_bool",
    "ast_transform", "Dy2StaticTransformer", "UNDEFINED",
]


class _Undefined:
    """Placeholder for names not assigned on one branch (the reference's
    UndefinedVar, dy2static/utils.py). Reading it outside a converted
    region is an error surfaced lazily."""

    def __repr__(self):
        return "<undefined>"

    def __bool__(self):
        raise NameError(
            "variable is undefined on one branch of a converted `if`; "
            "assign it on both branches (dy2static)")


UNDEFINED = _Undefined()


def _is_traced(x) -> bool:
    arr = x._data if isinstance(x, Tensor) else x
    return isinstance(arr, jax.core.Tracer)


def _to_bool(x) -> bool:
    if isinstance(x, Tensor):
        return bool(x._data)
    return bool(x)


def _unwrap(v):
    return v._data if isinstance(v, Tensor) else v


def _carry_encode(vals: Sequence[Any]):
    """Split carries into traced-array payload + static python template.

    lax.cond/while_loop carries must be arrays; python scalars ride as
    weak-typed arrays, anything else must be identical across branches /
    loop-invariant (kept static)."""
    payload, template = [], []
    for v in vals:
        if isinstance(v, Tensor):
            payload.append(v._data)
            template.append(("tensor", None))
        elif isinstance(v, jax.Array) or isinstance(v, jax.core.Tracer):
            payload.append(v)
            template.append(("array", None))
        elif isinstance(v, bool):
            payload.append(jnp.asarray(v))
            template.append(("bool", None))
        elif isinstance(v, (int, float)):
            payload.append(jnp.asarray(v))
            template.append((type(v).__name__, None))
        else:
            payload.append(None)
            template.append(("static", v))
    return payload, template


def _carry_decode(payload, template):
    """payload is ALIGNED with template (None at static positions)."""
    out = []
    for (kind, static), pv in zip(template, payload):
        if kind == "static":
            out.append(static)
        elif kind == "tensor":
            out.append(Tensor(pv))
        else:
            out.append(pv)
    return out


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable, args=()):
    """Runtime for rewritten `if`. Branch fns receive the pre-branch values
    of every name either branch assigns and return their post-branch values
    (reference: convert_operators.py convert_ifelse).

    Traced path: the branches run INSIDE lax.cond's callables, so the
    backward pass differentiates only the branch that was taken — guarded
    math like `if ok: y = sqrt(h) else: y = ...` must not leak NaN
    cotangents from the untaken branch. Structure discovery uses an
    abstract jax.eval_shape probe (no FLOPs, no gradients)."""
    if not _is_traced(pred):
        return true_fn(*args) if _to_bool(pred) else false_fn(*args)

    a_pay, a_tmpl = _carry_encode(list(args))
    a_live = [i for i, p in enumerate(a_pay) if p is not None]
    live_args = tuple(jnp.asarray(a_pay[i]) for i in a_live)

    def _lift_args(arrays):
        full = list(a_pay)
        for i, a in zip(a_live, arrays):
            full[i] = a
        return tuple(_carry_decode(full, a_tmpl))

    boxes = {}

    def _runner(fn, tag):
        """Run a branch on operand arrays; record (template, was_tuple) in
        boxes[tag]; return the payload arrays only."""
        def run(arrays):
            out = fn(*_lift_args(arrays))
            tup = out if isinstance(out, tuple) else (out,)
            pay, tmpl = _carry_encode(list(tup))
            boxes[tag] = (tmpl, isinstance(out, tuple))
            return tuple(jnp.asarray(p) for p in pay if p is not None)
        return run

    run_t, run_f = _runner(true_fn, "t"), _runner(false_fn, "f")
    # abstract probe: fills boxes and yields shapes/dtypes for reconciliation
    t_shapes = jax.eval_shape(run_t, live_args)
    f_shapes = jax.eval_shape(run_f, live_args)
    t_tmpl, t_is_tuple = boxes["t"]
    f_tmpl, _ = boxes["f"]
    if len(t_tmpl) != len(f_tmpl):
        raise ValueError(
            "dy2static `if`: branches produced different numbers of "
            f"outputs ({len(t_tmpl)} vs {len(f_tmpl)})")

    # Reconcile position-wise (lax.cond needs one output structure):
    #  * both arrays: promote dtypes;
    #  * one side UNDEFINED (name assigned on the other branch only): fill
    #    with zeros — the name is semantically undefined on that path, any
    #    read of the garbage is a user bug (the reference's UndefinedVar
    #    contract, dy2static/utils.py);
    #  * both static: must agree.
    t_sh, f_sh = list(t_shapes), list(f_shapes)
    merged_tmpl, slots = [], []   # slots: (dtype, fill_shape) or None=static
    ti = fi = 0
    for (tk, tv), (fk, fv) in zip(t_tmpl, f_tmpl):
        if tk != "static" and fk != "static":
            dt = jnp.result_type(t_sh[ti].dtype, f_sh[fi].dtype)
            slots.append((dt, None))
            merged_tmpl.append(("tensor" if "tensor" in (tk, fk) else tk, None))
            ti += 1
            fi += 1
        elif tk != "static" and fv is UNDEFINED:
            slots.append((t_sh[ti].dtype, ("f", t_sh[ti].shape)))
            merged_tmpl.append((tk, None))
            ti += 1
        elif fk != "static" and tv is UNDEFINED:
            slots.append((f_sh[fi].dtype, ("t", f_sh[fi].shape)))
            merged_tmpl.append((fk, None))
            fi += 1
        elif tk == "static" and fk == "static":
            if tv is not fv and tv != fv:
                raise ValueError(
                    "dy2static `if` on a traced predicate: non-tensor output "
                    f"differs between branches ({tv!r} vs {fv!r}); make it a "
                    "tensor or move it out of the `if`")
            slots.append(None)
            merged_tmpl.append((tk, tv))
        else:
            raise ValueError(
                "dy2static `if` on a traced predicate: output is a tensor on "
                f"one branch but {tv if tk == 'static' else fv!r} on the other")

    def _branch(run, side):
        def callable_(arrays):
            pay = iter(run(arrays))
            outs = []
            for slot in slots:
                if slot is None:
                    continue
                dt, fill = slot
                if fill is not None and fill[0] == side:
                    outs.append(jnp.zeros(fill[1], dt))  # undefined here
                else:
                    outs.append(next(pay).astype(dt))
            return tuple(outs)
        return callable_

    p = _unwrap(pred)
    res = jax.lax.cond(jnp.reshape(p, ()).astype(bool),
                       _branch(run_t, "t"), _branch(run_f, "f"), live_args)
    it = iter(res)
    aligned = [next(it) if kind != "static" else None
               for kind, _ in merged_tmpl]
    out = tuple(_carry_decode(aligned, merged_tmpl))
    return out if t_is_tuple else out[0]


def convert_while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: tuple):
    """Runtime for rewritten `while`. `cond_fn(*vars)`, `body_fn(*vars) ->
    tuple(vars)`."""
    pred = cond_fn(*loop_vars)
    if not _is_traced(pred):
        vals = tuple(loop_vars)
        while _to_bool(cond_fn(*vals)):
            vals = body_fn(*vals)
            if not isinstance(vals, tuple):
                vals = (vals,)
        return vals

    payload, template = _carry_encode(list(loop_vars))
    live_idx = [i for i, p in enumerate(payload) if p is not None]

    def lift(arrays):
        full = []
        it = iter(arrays)
        for i, p in enumerate(payload):
            full.append(next(it) if p is not None else None)
        return tuple(_carry_decode(full, template))

    def lax_cond(carry):
        return jnp.reshape(_unwrap(cond_fn(*lift(carry))), ()).astype(bool)

    def lax_body(carry):
        outs = body_fn(*lift(carry))
        if not isinstance(outs, tuple):
            outs = (outs,)
        new_pay, _ = _carry_encode(list(outs))
        return tuple(jnp.asarray(new_pay[i]).astype(carry[j].dtype)
                     for j, i in enumerate(live_idx))

    # Promote the initial carry to the dtype one body pass produces (an
    # int32 x with `x = x / 2` must iterate in float like eager would; the
    # speculative trace is dead code for XLA). The loop itself then keeps
    # the promoted dtype fixed, as lax.while_loop requires.
    init = [jnp.asarray(payload[i]) for i in live_idx]
    probe = body_fn(*lift(init))
    if not isinstance(probe, tuple):
        probe = (probe,)
    probe_pay, _ = _carry_encode(list(probe))
    init = tuple(
        a if probe_pay[i] is None
        else a.astype(jnp.result_type(a, jnp.asarray(probe_pay[i])))
        for a, i in zip(init, live_idx))
    final = jax.lax.while_loop(lax_cond, lax_body, init)
    return lift(final)


def convert_logical_and(lhs_fn: Callable, rhs_fn: Callable):
    lhs = lhs_fn()
    if not _is_traced(lhs):
        return rhs_fn() if _to_bool(lhs) else lhs
    rhs = rhs_fn()
    from ..core import ops
    return ops.logical_and(_as_tensor(lhs), _as_tensor(rhs))


def convert_logical_or(lhs_fn: Callable, rhs_fn: Callable):
    lhs = lhs_fn()
    if not _is_traced(lhs):
        return lhs if _to_bool(lhs) else rhs_fn()
    rhs = rhs_fn()
    from ..core import ops
    return ops.logical_or(_as_tensor(lhs), _as_tensor(rhs))


def convert_logical_not(x):
    if not _is_traced(x):
        return not _to_bool(x)
    from ..core import ops
    return ops.logical_not(_as_tensor(x))


def normalize_range(*args):
    """Runtime for rewritten `for i in range(...)`: (start, stop, step)."""
    if len(args) == 1:
        return 0, args[0], 1
    if len(args) == 2:
        return args[0], args[1], 1
    return args


def range_cond(i, stop, step):
    """Loop-continue predicate honoring negative steps. A traced step's
    SIGN cannot be branched on at trace time — fail loudly rather than
    silently assuming positive."""
    if _is_traced(step):
        raise NotImplementedError(
            "dy2static for-range: the step must be a python int (its sign "
            "selects the loop predicate); got a traced tensor step")
    if step < 0:
        if _is_traced(i) or _is_traced(stop):
            from ..core import ops
            return ops.greater_than(_as_tensor(i), _as_tensor(stop))
        return i > stop
    if _is_traced(i) or _is_traced(stop):
        from ..core import ops
        return ops.less_than(_as_tensor(i), _as_tensor(stop))
    return i < stop


def convert_bool(x):
    """`bool(x)` in rewritten predicates: stays a tensor when traced."""
    if _is_traced(x):
        return x
    return _to_bool(x)


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


# ---------------------------------------------------------------------------
# AST analysis + rewrite
# ---------------------------------------------------------------------------

class _AssignedNames(ast.NodeVisitor):
    """Names bound by statements (Assign/AugAssign/For targets/With/...)."""

    def __init__(self):
        self.names = []

    def _add(self, target):
        if isinstance(target, ast.Name):
            if target.id not in self.names:
                self.names.append(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._add(e)

    def visit_Assign(self, node):
        for t in node.targets:
            self._add(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # don't descend into nested scopes
        self.names.append(node.name)

    def visit_Lambda(self, node):
        pass


_SYNTHETIC = re.compile(
    r"^__(true_fn|false_fn|loop_cond|loop_body|for_i|for_stop|for_step)_\d+$")


def _assigned(stmts: Sequence[ast.stmt]) -> List[str]:
    """Names bound by `stmts`, excluding the helper functions an earlier
    (nested) rewrite emitted — they are scaffolding, not user state."""
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return [n for n in v.names if not _SYNTHETIC.match(n)]


def _has_return(stmts: Sequence[ast.stmt]) -> bool:
    """A `return` at THIS function's level (not inside a nested def — the
    synthetic branch/loop helpers of an inner rewrite end in return)."""
    def scan(n) -> bool:
        if isinstance(n, ast.Return):
            return True
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
        return any(scan(c) for c in ast.iter_child_nodes(n))
    return any(scan(s) for s in stmts or [])


def _breaks_scope(stmts: Sequence[ast.stmt]) -> bool:
    """True if a break/continue at this level would escape a nested fn
    (not enclosed in a loop within `stmts`)."""
    def scan(stmt) -> bool:
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return True
        if isinstance(stmt, (ast.For, ast.While, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.Lambda)):
            return False  # enclosed by its own loop/scope
        return any(scan(c) for c in ast.iter_child_nodes(stmt)
                   if isinstance(c, (ast.stmt, ast.excepthandler)))
    return any(scan(s) for s in stmts or [])


_RT_NAME = "__paddle_tpu_dy2static_rt__"


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _call(func_attr: str, args, keywords=None):
    return ast.Call(
        func=ast.Attribute(value=_name(_RT_NAME), attr=func_attr, ctx=ast.Load()),
        args=list(args), keywords=keywords or [])


class Dy2StaticTransformer(ast.NodeTransformer):
    """The rewrite pipeline (reference: ast_transformer.py transformers
    collapsed into one pass)."""

    def __init__(self):
        self._counter = 0

    def _fresh(self, base):
        self._counter += 1
        return f"__{base}_{self._counter}"

    # --- logical operators keep short-circuit semantics via thunks --------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        expr = node.values[-1]
        for prev in reversed(node.values[:-1]):
            expr = _call(fn, [
                ast.Lambda(args=_no_args(), body=prev),
                ast.Lambda(args=_no_args(), body=expr)])
        return ast.copy_location(expr, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(
                _call("convert_logical_not", [node.operand]), node)
        return node

    def visit_IfExp(self, node):
        self.generic_visit(node)
        return ast.copy_location(_call("convert_ifelse", [
            node.test,
            ast.Lambda(args=_no_args(), body=node.body),
            ast.Lambda(args=_no_args(), body=node.orelse)]), node)

    # --- statements -------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        body_ret = _has_return(node.body)
        else_ret = _has_return(node.orelse)
        if body_ret or else_ret:
            return self._rewrite_if_with_return(node)

        if _breaks_scope(node.body) or _breaks_scope(node.orelse):
            return node  # break/continue escape a nested fn: leave to python

        out_names = sorted(set(_assigned(node.body)) | set(_assigned(node.orelse)))
        if not out_names:
            # branch bodies are pure side-effect python (e.g. appends);
            # only safe when the predicate is concrete — keep as-is
            return node

        true_name, false_name = self._fresh("true_fn"), self._fresh("false_fn")
        guards = [_define_guard(n) for n in out_names]
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(n) for n in out_names], ctx=ast.Load()))
        t_def = _fn_def(true_name, node.body + [ret], arg_names=out_names)
        f_def = _fn_def(false_name, (node.orelse or []) + [ret],
                        arg_names=out_names)
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_name(n, ast.Store()) for n in out_names],
                               ctx=ast.Store())],
            value=_call("convert_ifelse",
                        [node.test, _name(true_name), _name(false_name),
                         ast.Tuple(elts=[_name(n) for n in out_names],
                                   ctx=ast.Load())]))
        return [ast.copy_location(ast.fix_missing_locations(s), node)
                for s in (*guards, t_def, f_def, assign)]

    def _rewrite_if_with_return(self, node):
        """`if` where BOTH branches end in return and contain nothing after:
        rewrite to `return convert_ifelse(...)`. Anything more complex is
        left to Python (works for concrete predicates, clear error for
        traced ones)."""
        def only_return(stmts):
            return (len(stmts) >= 1 and isinstance(stmts[-1], ast.Return)
                    and not any(_has_return([s]) for s in stmts[:-1]))

        if not (only_return(node.body) and node.orelse
                and only_return(node.orelse)):
            return node
        t_name, f_name = self._fresh("true_fn"), self._fresh("false_fn")
        # pre-state of names either branch assigns rides in as parameters
        # (so `x += 1; return x` patterns see the outer value)
        arg_names = sorted(set(_assigned(node.body)) | set(_assigned(node.orelse)))
        guards = [_define_guard(n) for n in arg_names]
        t_def = _fn_def(t_name, node.body, arg_names=arg_names)
        f_def = _fn_def(f_name, node.orelse, arg_names=arg_names)
        ret = ast.Return(value=_call(
            "convert_ifelse", [node.test, _name(t_name), _name(f_name),
                               ast.Tuple(elts=[_name(n) for n in arg_names],
                                         ctx=ast.Load())]))
        return [ast.copy_location(ast.fix_missing_locations(s), node)
                for s in (*guards, t_def, f_def, ret)]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_return(node.body) or _breaks_scope(node.body):
            return node  # while/else, return/break/continue: python only
        # conservative carry set: every name the body assigns
        carry = sorted(set(_assigned(node.body)))
        if not carry:
            return node
        cond_name, body_name = self._fresh("loop_cond"), self._fresh("loop_body")
        guards = [_define_guard(n) for n in carry]
        cond_def = _fn_def(cond_name, [ast.Return(value=node.test)],
                           arg_names=carry)
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(n) for n in carry], ctx=ast.Load()))
        body_def = _fn_def(body_name, list(node.body) + [ret],
                           arg_names=carry)
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_name(n, ast.Store()) for n in carry],
                               ctx=ast.Store())],
            value=_call("convert_while_loop", [
                _name(cond_name), _name(body_name),
                ast.Tuple(elts=[_name(n) for n in carry], ctx=ast.Load())]))
        return [ast.copy_location(ast.fix_missing_locations(s), node)
                for s in (*guards, cond_def, body_def, assign)]

    def visit_For(self, node):
        """`for i in range(...)` -> while-style convert_while_loop (the
        reference LoopTransformer's for path); a traced trip count becomes
        lax.while_loop instead of raising on range(tracer). Non-range
        iterables keep Python semantics (trace-time unroll).

        The loop variable is assigned from an INTERNAL counter at the top
        of each iteration, so after the loop it holds the last iterated
        value (Python semantics), not the overshoot; zero iterations leave
        the prior binding untouched."""
        self.generic_visit(node)
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3
                and isinstance(node.target, ast.Name)
                and not node.orelse and not _has_return(node.body)
                and not _breaks_scope(node.body)):
            return node
        tgt = node.target.id
        ctr = self._fresh("for_i")
        stop_n, step_n = self._fresh("for_stop"), self._fresh("for_step")
        norm = ast.Assign(
            targets=[ast.Tuple(elts=[_name(ctr, ast.Store()),
                                     _name(stop_n, ast.Store()),
                                     _name(step_n, ast.Store())],
                               ctx=ast.Store())],
            value=_call("normalize_range", list(it.args)))
        carry = sorted((set(_assigned(node.body)) | {tgt, ctr})
                       - {stop_n, step_n})
        guards = [_define_guard(n) for n in carry if n != ctr]
        cond_name, body_name = self._fresh("loop_cond"), self._fresh("loop_body")
        cond_def = _fn_def(cond_name, [ast.Return(value=_call(
            "range_cond", [_name(ctr), _name(stop_n), _name(step_n)]))],
            arg_names=carry)
        set_tgt = ast.Assign(targets=[_name(tgt, ast.Store())],
                             value=_name(ctr))
        inc = ast.Assign(
            targets=[_name(ctr, ast.Store())],
            value=ast.BinOp(left=_name(ctr), op=ast.Add(),
                            right=_name(step_n)))
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(n) for n in carry], ctx=ast.Load()))
        body_def = _fn_def(body_name, [set_tgt] + list(node.body) + [inc, ret],
                           arg_names=carry)
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_name(n, ast.Store()) for n in carry],
                               ctx=ast.Store())],
            value=_call("convert_while_loop", [
                _name(cond_name), _name(body_name),
                ast.Tuple(elts=[_name(n) for n in carry], ctx=ast.Load())]))
        return [ast.copy_location(ast.fix_missing_locations(s), node)
                for s in (norm, *guards, cond_def, body_def, assign)]


def _no_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                         kw_defaults=[], kwarg=None, defaults=[])


def _arg_list(names):
    return ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=n, annotation=None) for n in names],
        vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])


def _fn_def(name, body, arg_names=()):
    """Version-safe FunctionDef: parse a template so fields new to the
    running Python (e.g. 3.12 type_params) are present, then splice body."""
    tmpl = ast.parse(f"def {name}({', '.join(arg_names)}):\n    pass").body[0]
    tmpl.body = list(body)
    return ast.fix_missing_locations(tmpl)


def _define_guard(name_id: str):
    """`try: name \n except NameError: name = _jst.UNDEFINED` — makes a name
    that is only assigned inside the converted region referenceable (the
    reference's UndefinedVar pre-declaration, dy2static/utils.py)."""
    g = ast.parse(
        f"try:\n    {name_id}\nexcept NameError:\n"
        f"    {name_id} = {_RT_NAME}.UNDEFINED").body[0]
    return ast.fix_missing_locations(g)


def ast_transform(fn: Callable) -> Callable:
    """Rewrite fn's source through Dy2StaticTransformer and return the new
    function bound to fn's globals+closure. Returns fn unchanged when the
    source is unavailable or the rewrite does not apply (builtins, lambdas,
    already-converted functions)."""
    if getattr(fn, "_not_to_static", False) or isinstance(fn, functools.partial):
        return fn
    inner = fn.__func__ if inspect.ismethod(fn) else fn
    if not isinstance(inner, types.FunctionType):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(inner))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn

    func_node = tree.body[0]
    if not isinstance(func_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    func_node.decorator_list = []  # run undecorated; to_static re-wraps
    new_tree = Dy2StaticTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)

    # The rewritten function must see the module's LIVE globals (a name
    # defined later in the module, recursion, monkeypatched helpers), so we
    # exec into a scratch namespace only to harvest the code object, then
    # rebuild the function on inner.__globals__ itself. The `_jst` runtime
    # is injected into the live module globals under its private name.
    scratch = {_RT_NAME: _runtime_namespace()}
    inner.__globals__[_RT_NAME] = _runtime_namespace()
    freevars = inner.__code__.co_freevars
    if freevars:
        # the wrapper re-declares freevars so the transformed def closes
        # over real cells; the cells are snapshotted from the current
        # closure. A freevar the outer scope has not bound yet (mutual
        # recursion at decoration time) cannot be honored — fall back.
        try:
            cell_values = [c.cell_contents for c in inner.__closure__]
        except ValueError:
            return fn
        wrapper_name = "__dy2static_closure_wrapper"
        wrap = ast.parse(f"def {wrapper_name}({', '.join(freevars)}):\n    pass")
        wrap_fn = wrap.body[0]
        wrap_fn.body = [new_tree.body[0],
                        ast.Return(value=_name(func_node.name))]
        ast.fix_missing_locations(wrap)
        code = compile(wrap, filename=f"<dy2static {inner.__name__}>",
                       mode="exec")
        exec(code, scratch)
        harvested = scratch[wrapper_name](*cell_values)
        new_fn = types.FunctionType(
            harvested.__code__, inner.__globals__, inner.__name__,
            inner.__defaults__, harvested.__closure__)
    else:
        code = compile(new_tree, filename=f"<dy2static {inner.__name__}>",
                       mode="exec")
        exec(code, scratch)
        harvested = scratch[func_node.name]
        new_fn = types.FunctionType(
            harvested.__code__, inner.__globals__, inner.__name__,
            inner.__defaults__, None)

    new_fn.__kwdefaults__ = inner.__kwdefaults__
    new_fn._dy2static_original = fn
    if inspect.ismethod(fn):
        return types.MethodType(new_fn, fn.__self__)
    return new_fn


class _RuntimeNS:
    normalize_range = staticmethod(normalize_range)
    range_cond = staticmethod(range_cond)
    convert_ifelse = staticmethod(convert_ifelse)
    convert_while_loop = staticmethod(convert_while_loop)
    convert_logical_and = staticmethod(convert_logical_and)
    convert_logical_or = staticmethod(convert_logical_or)
    convert_logical_not = staticmethod(convert_logical_not)
    convert_bool = staticmethod(convert_bool)
    UNDEFINED = UNDEFINED


def _runtime_namespace():
    return _RuntimeNS
