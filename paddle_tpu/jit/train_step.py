"""TrainStep — one fused XLA program for forward+backward+optimizer.

TPU-native replacement for the reference's training executors: where the
reference threads every op through InterpreterCore instruction lists
(framework/new_executor/interpretercore.cc) and fuses DP gradients with
EagerReducer buckets (distributed/collective/reducer.cc:1038), here the whole
step — loss, grads, clip, optimizer update — is ONE jitted function with
donated parameter/optimizer buffers: XLA fuses, schedules, overlaps
collectives, and reuses memory. Sharding comes from PartitionSpec annotations
on parameters (`Tensor.pspec`), so DP/TP/FSDP are all configurations of this
single code path (SURVEY §7 design mapping).

Numerics observability (r8): with `numerics=` enabled the step also carries
a per-layer stats tree (debugging.sentinel) — activation rows recorded by
instrumented sublayers while tracing, per-layer grad rows, the global
grad-norm, and an in-graph found-inf scalar — reduced on device to one
compact [rows, 6] float32 array returned as an ordinary output. The host
fetches it every N steps or on demand; the hot path pays a few reductions
and ZERO device->host syncs. `scaler=` threads GradScaler's
(scale, good, bad) through the step so dynamic loss scaling works under
jit: loss scaled in-graph, grads unscaled, the update select-skipped on
overflow, state advanced by the same pure rule the eager path uses.
"""
from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor, Parameter
from ..core import random as _random
from ..core import autograd
from ..profiler.timeline import current as _tl_current
from .api import (_swap_params, _trace_guard, _tree_unwrap, _tree_wrap,
                  _note_cache_miss)

_logger = logging.getLogger("paddle_tpu.jit.train_step")


def _spec_or_replicated(p):
    return p.pspec if getattr(p, "pspec", None) is not None else P()


def _opt_state_spec(p, optimizer):
    """Optimizer-state spec = param spec, further sharded over the ZeRO axis
    when distributed.sharding marked the optimizer (stage>=1): this is what
    turns XLA's grad all-reduce into reduce-scatter + sharded update —
    ZeRO 1/2 with no bespoke runtime (see distributed/sharding.py)."""
    spec = _spec_or_replicated(p)
    stage = getattr(optimizer, "_sharding_stage", 0)
    if stage >= 1:
        from ..distributed.sharding import _with_axis
        from ..distributed import mesh as _dmesh
        axis = getattr(optimizer, "_sharding_axis", "sdp")
        size = _dmesh.mesh_axis_size(axis)
        if size > 1:
            return _with_axis(spec, p.shape, axis, size)
    return spec


class TrainStep:
    """Compile `loss = loss_fn(model(*inputs), *labels)`-style steps.

    train_step = TrainStep(model, opt, loss_fn)   # loss_fn(batch...)->Tensor
    loss = train_step(x, y)                       # updates model in place

    With `mesh`, parameters/optimizer state are placed by their pspec
    annotations and batch inputs are sharded over `data_axes`.

    `numerics`: True or a debugging.NumericsConfig — thread the per-layer
    stats tree through the compiled step (see module docstring);
    `train_step.numerics_stats()` fetches the latest tree on demand.
    `scaler`: an amp.GradScaler — dynamic loss scaling entirely in-graph.
    """

    def __init__(self, model, optimizer, loss_fn: Callable, mesh: Optional[Mesh] = None,
                 data_axes=("dp",), donate: bool = True, grad_accum_steps: int = 1,
                 monitor=None, numerics=None, scaler=None, lint=None,
                 preemption=None, chaos=None, timeline=None, memz=None,
                 grad_comm: Optional[str] = None, grad_comm_chunk: int = 256,
                 grad_comm_stochastic: bool = False,
                 grad_comm_f32_fallback: Optional[Callable] = None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.data_axes = data_axes
        self.donate = donate
        self.grad_accum_steps = grad_accum_steps
        # profiler.StepMonitor: per-step wall/MFU/HBM telemetry + the
        # recompilation detector (assignable after construction too)
        self.monitor = monitor
        # resilience wiring: `preemption` (a resilience.PreemptionHandler)
        # is polled at every step boundary — the in-flight XLA launch
        # always completes, then the handler takes its emergency
        # checkpoint and raises Preempted. `chaos` (a resilience.Injector)
        # fires the `step.end` fault site so kill-at-step-k tests die at
        # exactly the boundary a real preemption would.
        self.preemption = preemption
        self.chaos = chaos
        # goodput accounting (profiler.timeline): the step records every
        # launch as a `compile` span (compile-cache miss calls — trace +
        # XLA compile dominate their wall) or a `step` span (goodput).
        # Falls back to the process-wide installed recorder when unset.
        self.timeline = timeline
        # HBM ledger (ISSUE 18): params/opt-state register as owners
        # after the first compile (opt state materializes lazily), and a
        # device allocation failure unwinding out of a launch dumps the
        # OOM post-mortem artifact before re-raising
        self.memz = memz
        self._memz_registered = False
        self._step_i = 0
        self._compiled = {}
        self._last_sig = {}     # kind -> last compiled shape signature

        self._scaler = scaler if (scaler is not None
                                  and scaler.is_enable()) else None
        self._numerics = None
        self._sentinel_handle = None
        self._act_paths = []      # activation row paths, filled at 1st trace
        self._grad_groups = []    # [(path, [param indices])]
        self._last_aux = None     # latest step's aux pytree (device arrays)
        self._last_loss_arr = None
        self._last_key = None
        self._last_batch_struct = None   # nested python batch (array leaves)

        self._param_names, self._params = [], []
        for name, p in model.named_parameters():
            if not p.stop_gradient:
                self._param_names.append(name)
                self._params.append(p)
        self._buffers = [b for _, b in model.named_buffers()]

        if numerics is not None:
            self.set_numerics(numerics)

        # explicit gradient-sync modes (ISSUE 20): None keeps the
        # partitioner's implicit f32 psum; "f32"/"int8" step OUT of
        # auto-sharding into a shard_map over the dp axis with one
        # collective per `_grad_groups` layer bucket — per-layer so the
        # latency-hiding scheduler overlaps them with backward, int8 with
        # per-chunk factored scales for the ~4x wire cut (EQuARX).
        self.grad_comm = grad_comm
        self.grad_comm_chunk = int(grad_comm_chunk)
        self.grad_comm_stochastic = bool(grad_comm_stochastic)
        self._comm_groups = None
        if grad_comm is not None:
            if grad_comm not in ("f32", "int8"):
                raise ValueError(f"grad_comm={grad_comm!r}: expected None, "
                                 "'f32' or 'int8'")
            if mesh is None:
                raise ValueError("grad_comm requires TrainStep(mesh=...) — "
                                 "there is no gradient sync to replace "
                                 "without a data-parallel mesh")
            if len(data_axes) != 1 or tuple(mesh.axis_names) != tuple(data_axes):
                raise ValueError(
                    f"grad_comm needs a pure data-parallel mesh whose only "
                    f"axis is {data_axes!r} (got mesh axes "
                    f"{tuple(mesh.axis_names)}): partial-manual shard_map "
                    "lowers through PartitionId, which this runtime's "
                    "partitioner rejects")
            if grad_accum_steps > 1:
                raise ValueError("grad_comm with grad_accum_steps>1 is not "
                                 "supported yet — the accumulation scan "
                                 "would need the sync inside its body")
            if not self._grad_groups:
                from ..debugging import grad_layer_groups
                self._grad_groups = grad_layer_groups(
                    self._param_names, type(model).__name__)
            from ..distributed.quant_collectives import build_comm_groups
            shapes = [tuple(p.shape) for p in self._params]
            if grad_comm == "int8":
                self._comm_groups = build_comm_groups(
                    self._param_names, shapes, self._grad_groups,
                    grad_comm_f32_fallback)
            else:
                # "f32": same per-layer-group bucketing, every leaf on the
                # f32 lane — isolates the overlap effect from quantization
                self._comm_groups = [(path, (), tuple(idxs))
                                     for path, idxs in self._grad_groups]

        # static analysis (analysis.GraphLint): True/"error"/GraphLint —
        # the step's pure function is audited ABSTRACTLY (no execution)
        # before its first compile; findings land on `lint_findings` and
        # guard mode raises GraphLintError pre-compile
        from ..analysis import GraphLint as _GraphLint
        self._lint = _GraphLint.coerce(lint)
        self._lint_done = False
        self.lint_findings = None
        # sharding lint (ISSUE 15): under a mesh the lint additionally
        # compiles the step and audits the post-SPMD HLO — the static
        # collective inventory + resharding/replication/CommPlan passes.
        # The latest audit (a analysis.ShardingAudit) lands here.
        self.comm_audit = None

        # optimizer state as pytree (init lazily so shapes match cast params)
        self._opt_state = None

    def set_numerics(self, numerics):
        """(Re)configure the numerics mode after construction: installs the
        layer sentinels + per-layer grad grouping and invalidates compiled
        executables so the stats tree joins the step outputs on the next
        compile. Pass None/False to disable."""
        from ..debugging import (NumericsConfig, check_layer_numerics,
                                 grad_layer_groups)
        self._numerics = NumericsConfig.coerce(numerics)
        if self._numerics is not None:
            if self._sentinel_handle is None:
                # idempotent: reuses hooks another handle already installed
                self._sentinel_handle = check_layer_numerics(self.model)
            if self._numerics.grad_stats and not self._grad_groups:
                self._grad_groups = grad_layer_groups(
                    self._param_names, type(self.model).__name__)
        if self._compiled:
            self._compiled.clear()
            # deliberate re-trace, not shape instability: reset the
            # recompile detector's signatures so it stays quiet
            self._last_sig.clear()

    # ------------------------------------------------------------------
    def _init_opt_state(self):
        def _init(p, name):
            try:
                return self.optimizer.init_state(p._data, param_obj=p,
                                                 name=name)
            except TypeError:   # optimizers with the older signature
                return self.optimizer.init_state(p._data)
        return [_init(p, n)
                for p, n in zip(self._params, self._param_names)]

    def _shard_param_tree(self, tree_template):
        if self.mesh is None:
            return None
        specs = []
        for p in self._params:
            specs.append(_spec_or_replicated(p))
        return specs

    def _placement(self, spec):
        # drop axis names the mesh doesn't have (a TP-annotated model run on
        # a dp-only mesh just replicates those dims)
        from ..distributed import mesh as _dmesh
        with _dmesh.mesh_scope(self.mesh):
            spec = _dmesh.filter_spec(*spec) if spec is not None else P()
        return NamedSharding(self.mesh, spec)

    def _to_global(self, arr, spec):
        """Place a host array onto the (possibly multi-host) mesh.

        Multi-process: jax.device_put cannot target non-addressable devices;
        host_local_array_to_global_array assembles the global array from each
        process's local piece — for axes sharded ACROSS hosts (e.g. dp over
        processes) the caller passes its local shard; for host-local axes
        (mp within a host) and replicated specs, the full array."""
        from ..distributed import mesh as _dmesh
        with _dmesh.mesh_scope(self.mesh):
            fspec = _dmesh.filter_spec(*spec) if spec is not None else P()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            return multihost_utils.host_local_array_to_global_array(
                arr, self.mesh, fspec)
        return jax.device_put(arr, NamedSharding(self.mesh, fspec))

    def _to_global_from_full(self, arr, spec):
        """Place a host array that EVERY process holds in full (params and
        optimizer state — same-seed init) onto the mesh: each process
        contributes exactly the slices its devices own
        (make_array_from_callback), so specs sharded over process-CROSSING
        axes (e.g. pipeline stages split across hosts) assemble correctly.
        host_local_array_to_global_array would instead CONCATENATE the full
        copies — doubling any param sharded across the process boundary.
        Data batches keep the host-local-shard convention (_to_global)."""
        from ..distributed import mesh as _dmesh
        with _dmesh.mesh_scope(self.mesh):
            fspec = _dmesh.filter_spec(*spec) if spec is not None else P()
        sh = NamedSharding(self.mesh, fspec)
        if jax.process_count() > 1:
            import numpy as _np
            host = _np.asarray(arr)  # lint: allow(tracer-asarray)
            return jax.make_array_from_callback(host.shape, sh,
                                                lambda idx: host[idx])
        return jax.device_put(arr, sh)

    def _apply_param_shardings(self):
        """place params/opt state by their pspec (once)."""
        if self.mesh is None:
            return
        for p in self._params:
            p._data = self._to_global_from_full(p._data,
                                                _spec_or_replicated(p))
        if self._opt_state is not None:
            for p, st in zip(self._params, self._opt_state):
                spec = _opt_state_spec(p, self.optimizer)
                for k in st:
                    st[k] = self._to_global_from_full(
                        st[k], self.optimizer.state_spec(p, k, st[k], spec))

    # ------------------------------------------------------------------
    def _build(self, treedef, ndims):
        opt = self.optimizer
        params = self._params
        pure_step = self._build_pure(treedef)

        kwargs = {}
        if self.mesh is not None:
            pspecs = tuple(_spec_or_replicated(p) for p in params)
            sspecs = tuple(_opt_state_spec(p, opt) for p in params)
            # per-entry spec comes from the optimizer (param-shaped state
            # follows the param; e.g. int8 moment codes shard their block
            # dim) — see Optimizer.state_spec
            state_specs = tuple(
                {k: opt.state_spec(params[i], k, self._opt_state[i][k],
                                   sspecs[i])
                 for k in (self._opt_state[i] or {})}
                for i in range(len(params)))
            flat_specs = [P(*self.data_axes) if nd > 0 else P() for nd in ndims]
            in_shardings = (
                tuple(self._placement(s) for s in pspecs),
                tuple({k: self._placement(s[k]) for k in s} for s in state_specs),
                None, None, None, None,
                *[self._placement(s) for s in flat_specs],
            )
            out_shardings = (
                None,
                tuple(self._placement(s) for s in pspecs),
                tuple({k: self._placement(s[k]) for k in s} for s in state_specs),
                None, None,
            )
            kwargs = dict(in_shardings=in_shardings, out_shardings=out_shardings)
        donate = (0, 1) if self.donate else ()
        return jax.jit(pure_step, donate_argnums=donate, **kwargs)

    # ------------------------------------------------------------------
    def _build_scan(self, treedef, n_steps):
        """N optimizer steps in ONE executable via lax.scan over stacked
        batches [n, ...]. Amortizes host dispatch (one launch per N steps)
        and lets XLA overlap step boundaries — the analog of the reference's
        gradient_merge/program-level multi-batch execution, and the honest
        way to benchmark on remote-dispatch runtimes. Numerics stats and the
        scaler state ride the scan (stats stacked [n, rows, 6]; scaler state
        as carry — per-step overflow decisions, same as N eager updates)."""
        single = self._build_pure(treedef)

        def multi(param_arrays, opt_state, scaler_state, step0, lr, key,
                  *flat_batches):
            def body(carry, xs):
                params, state, sstate, i = carry
                ks, batch_leaves = xs[0], xs[1:]
                loss, new_p, new_s, new_ss, aux = single(
                    params, state, sstate, i, lr, ks, *batch_leaves)
                return (new_p, new_s, new_ss, i + 1), (loss, aux)

            keys = jax.random.split(key, n_steps)
            (pa, st, ss, _), (losses, auxs) = jax.lax.scan(
                body,
                (tuple(param_arrays), tuple(opt_state), scaler_state, step0),
                (keys, *flat_batches))
            return losses, pa, st, ss, auxs

        return jax.jit(multi, donate_argnums=(0, 1))

    def _build_pure(self, treedef):
        """The single-step pure function (shared by __call__ and scan)."""
        opt = self.optimizer
        params = self._params
        loss_fn = self.loss_fn
        wds = [opt._wd_for(p) for p in params]
        grad_clip = opt._grad_clip
        accum = max(1, int(self.grad_accum_steps))
        numerics = self._numerics
        scaler = self._scaler
        grad_groups = self._grad_groups
        act_paths_box = self._act_paths
        grad_comm = self.grad_comm
        comm_groups = self._comm_groups
        if grad_comm is not None:
            from ..distributed import quant_collectives as _qc
            comm_axis = self.data_axes[0]
            comm_D = int(self.mesh.shape[comm_axis])
            comm_chunk = self.grad_comm_chunk
            comm_stoch = self.grad_comm_stochastic
            comm_mesh = self.mesh
        if numerics is not None or scaler is not None:
            from ..debugging import sentinel as _sentinel
        else:
            _sentinel = None

        def pure_step(param_arrays, opt_state, scaler_state, step_i, lr, key,
                      *flat_batch):
            batch = jax.tree.unflatten(treedef, flat_batch)
            scale = scaler_state[0] if scaler_state is not None else None

            def loss_of(pa, microbatch, k):
                import contextlib
                col_cm = _sentinel.collect_stats() if numerics is not None \
                    else contextlib.nullcontext()
                with _trace_guard(), _swap_params(params, list(pa)), \
                        _random.trace_key_scope(k), autograd.no_grad(), \
                        col_cm as col:
                    out = loss_fn(*_tree_wrap(microbatch))
                loss_arr = out._data if isinstance(out, Tensor) else out
                loss_arr = loss_arr.astype(jnp.float32)
                act_rows = None
                if numerics is not None:
                    act_rows = col.stacked()
                    if col.paths and not act_paths_box:
                        act_paths_box.extend(col.paths)
                # loss scaling happens in-graph: autodiff sees the SCALED
                # loss, the aux carries the true loss back out
                scaled = loss_arr * scale if scale is not None else loss_arr
                return scaled, (loss_arr, act_rows)

            if accum == 1 and grad_comm is not None:
                # explicit gradient sync (ISSUE 20): shard_map manual over
                # the dp axis — per-shard backward on the local microbatch,
                # then one collective per layer group (int8 psum with
                # per-chunk scales, or the f32 twin), so the scheduler can
                # overlap group N's all-reduce with layer N-1's backward
                from jax import shard_map as _shard_map
                from jax import lax as _lax

                def _shard_step(pa, b, k):
                    # the region is MANUAL over the dp axis: the model's
                    # activation shard_constraints (global-mesh specs) are
                    # illegal here — and on the pure-dp mesh grad_comm
                    # requires they pin nothing the manual region doesn't
                    # already fix, so trace the loss with no active mesh
                    from ..distributed import mesh as _dmesh
                    with _dmesh.mesh_scope(None):
                        (_, (l, rows)), g = jax.value_and_grad(
                            loss_of, has_aux=True)(list(pa), b, k)
                    sk = jax.random.fold_in(k, 0x5C) if comm_stoch else None
                    g = _qc.sync_grad_groups(
                        g, comm_groups, comm_axis, comm_D,
                        chunk=comm_chunk, stochastic=comm_stoch, key=sk)
                    l = _lax.pmean(l, comm_axis)
                    if rows is not None:
                        rows = _lax.pmean(rows, comm_axis)
                    return l, rows, g

                bspec = jax.tree.map(
                    lambda a: P(comm_axis) if getattr(a, "ndim", 0) > 0
                    else P(), batch)
                loss, act_rows, grads = _shard_map(
                    _shard_step, mesh=comm_mesh, axis_names={comm_axis},
                    in_specs=(P(), bspec, P()),
                    out_specs=(P(), P(), [P()] * len(params)),
                    check_vma=False)(list(param_arrays), batch, key)
            elif accum == 1:
                (_, (loss, act_rows)), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(list(param_arrays), batch, key)
            else:
                # gradient accumulation (reference: gradient_merge /
                # GradientMergeOptimizer): split the batch dim into `accum`
                # microbatches, scan fwd+bwd accumulating mean grads, ONE
                # optimizer update — same memory as a 1/accum-size batch
                def to_micro(a):
                    if a.ndim == 0:
                        raise ValueError(
                            "grad_accum_steps requires batched inputs; got a "
                            "scalar batch leaf")
                    if a.shape[0] % accum:
                        raise ValueError(
                            f"batch size {a.shape[0]} is not divisible by "
                            f"grad_accum_steps={accum}")
                    return a.reshape((accum, a.shape[0] // accum) + a.shape[1:])

                micro = jax.tree.map(to_micro, batch)
                keys = jax.random.split(key, accum)

                def acc_body(carry, xs):
                    loss_acc, g_acc = carry
                    mb, k = xs
                    (_, (l, rows)), g = jax.value_and_grad(
                        loss_of, has_aux=True)(list(param_arrays), mb, k)
                    return (loss_acc + l / accum,
                            [ga + (gi / accum).astype(ga.dtype)
                             for ga, gi in zip(g_acc, g)]), rows

                # accumulate in the PARAM dtype: autodiff grads already come
                # out in param dtype (bf16 for bf16 models), and an f32
                # accumulator would double the grad footprint — the very
                # memory the microbatching exists to save
                zeros = [jnp.zeros(p.shape, p.dtype)
                         for p in param_arrays]
                (loss, grads), micro_rows = jax.lax.scan(
                    acc_body, (jnp.float32(0.0), zeros), (micro, keys))
                act_rows = None if micro_rows is None else \
                    _sentinel.merge_stacked(micro_rows)

            # unscale BEFORE clip/sentinels so grad stats and the update see
            # true gradients (found-inf is scale-invariant)
            if scale is not None:
                inv = jnp.float32(1.0) / scale
                grads = [g * inv.astype(g.dtype) for g in grads]

            aux = {}
            found = None
            need_found = scaler is not None or (
                numerics is not None and numerics.skip_nonfinite_updates)
            if numerics is not None:
                rows = list(act_rows) if act_rows is not None else []
                grow_mat = None
                if grad_groups:
                    _, grows = _sentinel.grad_stat_rows(grads, grad_groups)
                    rows += grows
                    grow_mat = jnp.stack(grows)
                if rows:
                    aux["stats"] = jnp.stack(rows)
                if grow_mat is not None:
                    # found-inf and the global grad-norm DERIVE from the
                    # grad rows — no second scan over grad memory (the rows
                    # mask non-finites out of l2, so the norm stays finite
                    # and the nan/inf counts carry the overflow signal)
                    if need_found:
                        found = jnp.sum(grow_mat[:, 1] + grow_mat[:, 2]) > 0
                    aux["grad_norm"] = jnp.sqrt(
                        jnp.sum(grow_mat[:, 5] ** 2))
                else:
                    if need_found:
                        found = _sentinel.found_inf(grads)
                    aux["grad_norm"] = jnp.sqrt(
                        sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                            for g in grads))
                if found is not None:
                    aux["found_inf"] = found
            elif need_found:
                found = _sentinel.found_inf(grads)
                aux["found_inf"] = found
            if grad_clip is not None and type(grad_clip).__name__ == "ClipGradByGlobalNorm":
                total = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                                     for g in grads))
                scale_c = jnp.minimum(1.0, grad_clip.clip_norm / jnp.maximum(total, 1e-12))
                grads = [g * scale_c.astype(g.dtype) for g in grads]
            new_params = [None] * len(param_arrays)
            new_state = [None] * len(param_arrays)
            # fused multi-tensor apply (reference analog:
            # distributed_fused_lamb.py:82): concatenate each (dtype,
            # moment-dtype) group of small params into ONE flat elementwise
            # update; weight decay becomes a per-element constant vector.
            # MEASURED OFF by default on v5e: XLA already fuses per-param
            # updates into the weight-grad producing fusions, and the
            # separate flattened pass DEFEATS that — GPT-1.3B break-even
            # (73.4 vs 73.6% MFU), ResNet-50 −12% (1471 vs 1681 img/s).
            # Kept as an opt-in (PADDLE_TPU_FUSE_SMALL_UPDATES=<bytes>)
            # for runtimes where the trade lands differently.
            import os as _os
            fuse_t = int(_os.environ.get("PADDLE_TPU_FUSE_SMALL_UPDATES",
                                         "0"))
            groups = {}
            fkeys = tuple(getattr(opt, "_fused_state_keys", ()))
            if getattr(opt, "_fusable_elementwise", False) and fuse_t > 0:
                for i, (pa, st) in enumerate(zip(param_arrays, opt_state)):
                    if (pa.size <= fuse_t and pa.ndim >= 1
                            and st is not None and set(st) == set(fkeys)):
                        key_g = (str(pa.dtype),) + tuple(
                            str(st[k].dtype) for k in fkeys)
                        groups.setdefault(key_g, []).append(i)
            fused_idx = set()
            for idxs in groups.values():
                if len(idxs) < 2:
                    continue
                fused_idx.update(idxs)
                sizes = [param_arrays[i].size for i in idxs]
                offs = [0]
                for s_ in sizes:
                    offs.append(offs[-1] + s_)
                flat_p = jnp.concatenate(
                    [param_arrays[i].reshape(-1) for i in idxs])
                flat_g = jnp.concatenate(
                    [grads[i].reshape(-1) for i in idxs])
                flat_st = {
                    k: jnp.concatenate(
                        [opt_state[i][k].reshape(-1) for i in idxs])
                    for k in fkeys}
                wd_vec = jnp.concatenate(
                    [jnp.full((param_arrays[i].size,), float(wds[i]),  # lint: allow(tracer-float)
                              jnp.float32) for i in idxs])
                fp, fs = opt.update(flat_p, flat_g, flat_st, lr, step_i,
                                    wd_vec)
                for j, i in enumerate(idxs):
                    sl = slice(offs[j], offs[j + 1])
                    new_params[i] = fp[sl].reshape(param_arrays[i].shape)
                    new_state[i] = {
                        k: fs[k][sl].reshape(opt_state[i][k].shape)
                        for k in fkeys}
            for i, (pa, g, st, wd) in enumerate(
                    zip(param_arrays, grads, opt_state, wds)):
                if i in fused_idx:
                    continue
                np_, ns_ = opt.update(pa, g, st, lr, step_i, wd)
                new_params[i] = np_
                new_state[i] = ns_
            # select-skip the update on overflow: params/opt-state never
            # ingest a non-finite value (GradScaler semantics; also what
            # makes an anomaly dump hold the exact pre-step state)
            if found is not None:
                new_params = [jnp.where(found, pa, np_)
                              for pa, np_ in zip(param_arrays, new_params)]
                new_state = [
                    ({k: jnp.where(found, st[k], ns_[k]) for k in ns_}
                     if ns_ and st else ns_)
                    for st, ns_ in zip(opt_state, new_state)]
            new_scaler_state = None
            if scaler_state is not None:
                from ..amp.grad_scaler import GradScaler
                new_scaler_state = GradScaler._update_rule(
                    *scaler_state, found, **scaler._hyper())
            return (loss, tuple(new_params), tuple(new_state),
                    new_scaler_state, aux)

        return pure_step

    # ------------------------------------------------------------------
    def _on_compile(self, kind: str, sig):
        """Compile-cache miss bookkeeping: feed the global jit miss counter
        and the recompilation detector — a second compile of the same kind
        means the abstract shape signature changed, and the delta names the
        offending leaf (the thing you want when a training loop silently
        recompiles every step)."""
        _note_cache_miss()
        prev = self._last_sig.get(kind)
        self._last_sig[kind] = sig
        if self.monitor is not None:
            self.monitor.record_compile(kind, sig, prev_sig=prev)
        elif prev is not None and prev != sig:
            from ..profiler.monitor import shape_delta
            _logger.warning("recompilation of %s: %s", kind,
                            shape_delta(prev, sig))

    # ------------------------------------------------------------------
    # numerics: fetch / detect / dump
    @property
    def numerics_paths(self):
        """Stats-tree row names: activation paths (trace order) then
        per-layer grad rows. Populated after the first compile."""
        return list(self._act_paths) + [k for k, _ in self._grad_groups]

    def numerics_stats(self, sync: bool = True):
        """The latest step's StatsTree (device->host fetch happens HERE, not
        in the step). None before the first numerics-enabled step."""
        if self._last_aux is None or "stats" not in self._last_aux:
            return None
        from ..debugging import StatsTree
        vals = self._last_aux["stats"]
        return StatsTree(self.numerics_paths,
                         np.asarray(vals) if sync else vals,  # lint: allow(tracer-asarray)
                         step=self._step_i)

    def _scaler_state_in(self):
        return self._scaler.state_arrays() if self._scaler is not None else None

    def _after_step(self, loss_arr, new_scaler_state, aux, *, steps=1):
        if self._scaler is not None and new_scaler_state is not None:
            self._scaler.set_state_arrays(
                new_scaler_state, found_inf=aux.get("found_inf"))
        if self._numerics is None:
            return
        self._last_aux = aux
        self._last_loss_arr = loss_arr
        cfg = self._numerics
        n = cfg.every_n_steps
        if n and (self._step_i % n == 0
                  or (steps > 1 and self._step_i % n < steps)):
            self._fetch_and_detect()

    def _fetch_and_detect(self):
        """One host fetch of the latest stats + loss/grad-norm scalars, run
        the detectors, route events (monitor / on_event / dump / raise)."""
        cfg = self._numerics
        tree = self.numerics_stats()
        loss = None
        if self._last_loss_arr is not None:
            la = np.asarray(self._last_loss_arr)  # lint: allow(tracer-asarray)
            loss = float(la.reshape(-1)[-1])  # run_steps: last step's loss  # lint: allow(tracer-float)
        gn = self._last_aux.get("grad_norm") if self._last_aux else None
        gn = float(np.asarray(gn).reshape(-1)[-1]) if gn is not None else None  # lint: allow(tracer-float, tracer-asarray)
        events = cfg.detector.observe(self._step_i, tree=tree, loss=loss,
                                      grad_norm=gn)
        monitor = cfg.monitor or self.monitor
        if monitor is not None and hasattr(monitor, "record_numerics"):
            monitor.record_numerics(step=self._step_i, loss=loss,
                                    grad_norm=gn, events=events)
        for e in events:
            _logger.warning("numerics: %r", e)
            if cfg.on_event is not None:
                cfg.on_event(e)
        if events and cfg.dump_dir:
            self._write_dump(events, tree, loss)
        if cfg.raise_on_nonfinite and any(
                e.kind in ("nan", "inf") for e in events):
            bad = next(e for e in events if e.kind in ("nan", "inf"))
            raise FloatingPointError(
                f"non-finite values detected at step {self._step_i} in "
                f"{bad.path}: {bad.message} (numerics.raise_on_nonfinite)")
        return events

    def _write_dump(self, events, tree, loss):
        from ..debugging import dump as _dump
        leaves, _ = jax.tree.flatten(self._last_batch_struct)
        spec = _dump.tree_spec(self._last_batch_struct)
        path = _dump.write_dump(
            self._numerics.dump_dir, step=self._step_i, events=events,
            batch_leaves=leaves, batch_spec=spec,
            param_names=self._param_names,
            param_arrays=[p._data for p in self._params],
            opt_state=self._opt_state, key=self._last_key, loss=loss,
            stats=tree,
            extra_meta={"model": type(self.model).__name__,
                        "skip_nonfinite_updates":
                            self._numerics.skip_nonfinite_updates})
        _logger.warning("numerics: dumped failing step %d to %s",
                        self._step_i, path)
        return path

    # ------------------------------------------------------------------
    # resilience: step-boundary hooks + the resumable state snapshot
    def _post_step(self):
        """Step-boundary resilience hooks, in hazard order: the chaos
        injector's `step.end` site first (a simulated kill must not get
        the checkpoint a real SIGKILL wouldn't), then the preemption
        poll (emergency checkpoint + Preempted)."""
        if self.chaos is not None:
            self.chaos.fire("step.end", step=self._step_i)
        if self.preemption is not None:
            self.preemption.poll(
                state=self.preemption.state or self, step=self._step_i)

    def state_dict(self) -> Dict:
        """Host snapshot of everything the COMPILED step owns: step
        counter, parameter arrays, the step's own optimizer-state pytree
        (not optimizer._states — the jitted path never touches those),
        host-side optimizer scalars (master step + LR-scheduler state) and
        the GradScaler triple. The device→host gather here is the ONE
        deliberate sync of the checkpoint path — at save time syncing is
        the job (allowlisted in the r11 source lint)."""
        out: Dict = {"step": int(self._step_i)}
        out["params"] = {
            n: np.asarray(p._data)  # lint: allow(tracer-asarray)
            for n, p in zip(self._param_names, self._params)}
        if self._opt_state is not None:
            out["opt"] = {
                n: {k: np.asarray(v)  # lint: allow(tracer-asarray)
                    for k, v in (st or {}).items()}
                for n, st in zip(self._param_names, self._opt_state)}
        extra: Dict = {"master_step": int(self.optimizer._step_count)}
        from ..optimizer.lr import LRScheduler as _LRS
        if isinstance(self.optimizer._lr, _LRS):
            extra["lr_sched"] = {
                k: v for k, v in self.optimizer._lr.state_dict().items()
                if isinstance(v, (bool, int, float, str))}
        out["opt_extra"] = extra
        if self._scaler is not None:
            out["scaler"] = self._scaler.state_dict()
        return out

    def set_state_dict(self, state: Dict):
        """Adopt a state_dict() snapshot: params/opt state land back on
        device (re-sharded by pspec under a mesh) with their saved dtypes
        — the compiled executables keep matching, so a resume costs one
        re-trace of a fresh TrainStep object and zero steady-state
        recompiles after."""
        params = state.get("params", {})
        missing = [n for n in self._param_names if n not in params]
        if missing:
            raise KeyError(f"checkpoint is missing parameters: "
                           f"{missing[:5]}{'...' if len(missing) > 5 else ''}")
        for n, p in zip(self._param_names, self._params):
            p._data = jnp.asarray(params[n])
            p._node = None
        opt = state.get("opt")
        if opt is not None:
            if self._opt_state is None:
                self._opt_state = self._init_opt_state()
            self._opt_state = [
                {k: jnp.asarray(v) for k, v in opt.get(n, {}).items()}
                or st
                for n, st in zip(self._param_names, self._opt_state)]
        self._apply_param_shardings()
        self._step_i = int(state.get("step", 0))
        extra = state.get("opt_extra", {})
        if "master_step" in extra:
            self.optimizer._step_count = int(extra["master_step"])
        if "lr_sched" in extra:
            from ..optimizer.lr import LRScheduler as _LRS
            if isinstance(self.optimizer._lr, _LRS):
                self.optimizer._lr.set_state_dict(dict(extra["lr_sched"]))
        if self._scaler is not None and "scaler" in state:
            self._scaler.set_state_dict(dict(state["scaler"]))
        return self

    # ------------------------------------------------------------------
    def lint(self, *batch, lint=None):
        """Statically audit the compiled step over this batch's shapes:
        trace (never execute) the pure step function through the
        analysis suite — host-transfer, dtype-promotion, baked-const and
        donation passes, with tracing under the transfer guard so an
        implicit `.item()` in a layer names its path. `batch` leaves may
        be Tensors, arrays, or jax.ShapeDtypeStructs. Returns Findings
        (also stored on `self.lint_findings`); a guard-mode linter
        raises GraphLintError. Works standalone (`TrainStep(...).lint(x,
        y)`) — `TrainStep(lint=...)` runs the same audit automatically
        before the first compile."""
        from ..analysis import GraphLint
        linter = GraphLint.coerce(lint) or self._lint or GraphLint()
        arrays = _tree_unwrap(batch)
        flat, treedef = jax.tree.flatten(arrays)
        return self._lint_check(linter, treedef, flat)

    @staticmethod
    def _sds(a):
        return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype) \
            if hasattr(a, "shape") else a

    def _abstract_step_args(self):
        """(params, opt state, scaler state) as ShapeDtypeStructs — the
        abstract leading arguments of the pure/built step, shared by the
        abstract lint and the sharded audit."""
        p_sds = tuple(self._sds(p._data) for p in self._params)
        s_sds = tuple({k: self._sds(v) for k, v in (st or {}).items()}
                      for st in self._opt_state)
        sstate = None
        if self._scaler is not None:
            sstate = tuple(jax.ShapeDtypeStruct((), d)
                           for d in (jnp.float32, jnp.int32, jnp.int32))
        return p_sds, s_sds, sstate

    @staticmethod
    def _plan_guard(linter, findings):
        """Guard-mode raise for CommPlan violations — the sharper
        CommPlanError, ahead of the generic GraphLintError guard."""
        if linter.mode != "error":
            return
        from ..analysis import CommPlanError
        plan_active = findings.for_pass("comm_plan").active(linter.fail_on)
        if plan_active:
            raise CommPlanError(plan_active, "train_step")

    def _lint_check(self, linter, treedef, flat):
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()
            self._apply_param_shardings()
        pure = self._build_pure(treedef)
        sds = self._sds
        p_sds, s_sds, sstate = self._abstract_step_args()
        built = None
        if self.mesh is not None:
            # under a mesh the abstract passes audit the BUILT jitted
            # step (shardings + donation baked in): lowering the bare
            # pure function would mix in-graph sharding constraints with
            # unsharded parameters, and the donation pass would report
            # aliasing misses the real executable does not have
            built = self._build(
                treedef,
                [getattr(a, "ndim", len(getattr(a, "shape", ())))
                 for a in flat])
        findings = linter.check(
            built if built is not None else pure,
            p_sds, s_sds, sstate, jnp.int32(1), jnp.float32(1e-3),
            jax.random.PRNGKey(0), *[sds(a) for a in flat],
            # audit the donation config the REAL executable uses — with
            # donate=False the pass must report the donatable params/state,
            # not prove an aliasing the step doesn't have
            donate_argnums=(0, 1) if self.donate else (),
            name="train_step", guard=False)
        if self.mesh is not None:
            audit = self._sharded_audit(linter, treedef, flat, sstate,
                                        built=built)
            findings.extend(audit.findings)
        # stored BEFORE the guard fires: a caller catching GraphLintError
        # can still read step.lint_findings post-mortem
        self.lint_findings = findings
        self._plan_guard(linter, findings)
        linter._guard(findings, "train_step")
        return findings

    def _sharded_audit(self, linter, treedef, flat, sstate=None,
                       built=None):
        """The sharded half of the lint (ISSUE 15): build the jitted
        step with its REAL in/out shardings, lower + compile it with
        abstract inputs (nothing executes), and audit the
        post-partitioning HLO — collective inventory, resharding and
        replication passes, and the linter's CommPlan if one is
        declared. Entry-parameter keypaths translate back to model
        parameter names so a finding names the offending LAYER."""
        if built is None:
            built = self._build(
                treedef,
                [getattr(a, "ndim", len(getattr(a, "shape", ())))
                 for a in flat])
        sds = self._sds
        p_sds, s_sds, _ = self._abstract_step_args()
        key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
        names = {f"param_arrays[{i}]": n
                 for i, n in enumerate(self._param_names)}
        for i, n in enumerate(self._param_names):
            for k in (self._opt_state[i] or {}):
                names[f"opt_state[{i}][{k!r}]"] = f"{n}/{k}"
        audit = linter.check_sharded(
            built, p_sds, s_sds, sstate,
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.float32), key,
            *[sds(a) for a in flat],
            name="train_step", param_names=names,
            mesh_axes=dict(self.mesh.shape), guard=False)
        self.comm_audit = audit
        return audit

    def sharding_audit(self, *batch, lint=None, plan=None):
        """The sharded audit alone (ISSUE 15): compile the step under
        its mesh for this batch's shapes and statically inventory /
        lint its collectives. Returns the analysis.ShardingAudit (also
        on `self.comm_audit`); `plan` overrides the linter's CommPlan.
        Requires a mesh — without one there is no SPMD partition to
        audit."""
        if self.mesh is None:
            raise ValueError("sharding_audit requires TrainStep(mesh=...) "
                             "— an unsharded step has no communication "
                             "plan to prove")
        from ..analysis import GraphLint
        linter = GraphLint.coerce(lint) or self._lint or GraphLint()
        if plan is not None:
            import copy
            linter = copy.copy(linter)
            linter.comm_plan = plan
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()
            self._apply_param_shardings()
        arrays = _tree_unwrap(batch)
        flat, treedef = jax.tree.flatten(arrays)
        _, _, sstate = self._abstract_step_args()
        audit = self._sharded_audit(linter, treedef, flat, sstate)
        self._plan_guard(linter, audit.findings)
        linter._guard(audit.findings, "train_step")
        return audit

    def _maybe_lint(self, treedef, flat):
        """TrainStep(lint=...): one audit before the first compile (the
        guard-mode raise happens while nothing has executed yet)."""
        if self._lint is None or self._lint_done:
            return
        self._lint_done = True
        self._lint_check(self._lint, treedef, flat)

    # ------------------------------------------------------------------
    def loss_and_grad_norm(self, *batch, key=None):
        """(loss, global grad norm) WITHOUT updating — the distributed-vs-
        single-device parity probe (reference strategy: test_dist_base.py:899
        compares distributed loss against a single-process replay). Pass the
        same `key` to both runs for identical dropout/rng."""
        params = self._params
        loss_fn = self.loss_fn
        arrays = _tree_unwrap(batch)
        flat, treedef = jax.tree.flatten(arrays)
        key_sig = ("lgn", treedef,
                   tuple((tuple(a.shape), str(a.dtype)) for a in flat))
        cached = self._compiled.get(key_sig)
        if cached is not None:
            if self.mesh is not None:
                flat = [self._to_global(a, P(*self.data_axes))
                        if a.ndim > 0 else a for a in flat]
            loss, gn = cached(tuple(p._data for p in params),
                              key if key is not None else jax.random.PRNGKey(0),
                              *flat)
            return float(loss), float(gn)

        def f(param_arrays, k, *flat_batch):
            b = jax.tree.unflatten(treedef, flat_batch)

            def loss_of(pa):
                with _trace_guard(), _swap_params(params, list(pa)), \
                        _random.trace_key_scope(k), autograd.no_grad():
                    out = loss_fn(*_tree_wrap(b))
                arr = out._data if isinstance(out, Tensor) else out
                return arr.astype(jnp.float32)

            loss, grads = jax.value_and_grad(loss_of)(list(param_arrays))
            gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                              for g in grads))
            return loss, gn

        kwargs = {}
        if self.mesh is not None:
            pspecs = tuple(_spec_or_replicated(p) for p in params)
            flat_specs = [P(*self.data_axes) if a.ndim > 0 else P()
                          for a in flat]
            kwargs = dict(in_shardings=(
                tuple(self._placement(s) for s in pspecs), None,
                *[self._placement(s) for s in flat_specs]))
            if self._opt_state is None:
                self._opt_state = self._init_opt_state()
            self._apply_param_shardings()
            flat = [self._to_global(a, P(*self.data_axes))
                    if a.ndim > 0 else a for a in flat]
        if key is None:
            key = jax.random.PRNGKey(0)
        compiled = jax.jit(f, **kwargs)
        self._compiled[key_sig] = compiled
        loss, gn = compiled(tuple(p._data for p in params), key, *flat)
        return float(loss), float(gn)

    def _abstract_opt_state(self):
        """Optimizer-state tree as ShapeDtypeStructs — no arrays allocated
        (jax.eval_shape over init_state). Lets memory planning for very
        large models run without materializing moments."""
        out = []
        for p, n in zip(self._params, self._param_names):
            sds = jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)

            def init(a, _p=p, _n=n):
                try:
                    return self.optimizer.init_state(a, param_obj=_p, name=_n)
                except TypeError:
                    return self.optimizer.init_state(a)

            out.append(jax.eval_shape(init, sds))
        return out

    def memory_plan(self, axes: Optional[Dict[str, int]] = None) -> Dict:
        """Analytic per-device HBM accounting from shapes + PartitionSpecs
        (the "jax.eval_shape math" plan; reference capability anchor:
        group_sharded_stage3.py:60 gather-on-use memory arithmetic).

        axes: mesh axis sizes to divide by — defaults to self.mesh's. Pass a
        hypothetical dict (e.g. a v4-64 factorization) to extrapolate the
        plan to meshes this host cannot build. Returns bytes/device for
        params, grads (same layout as params), and optimizer state.
        """
        if axes is None:
            axes = dict(self.mesh.shape) if self.mesh is not None else {}

        def div_of(spec, shape):
            d = 1
            for e, s in zip(tuple(spec or ()), shape):
                names = (e,) if isinstance(e, str) else tuple(e or ())
                for nm in names:
                    d *= axes.get(nm, 1)
            return d

        state = self._opt_state or self._abstract_opt_state()
        plan = {"params": 0, "grads": 0, "opt_state": 0}
        for p, st in zip(self._params, state):
            spec = _spec_or_replicated(p)
            nbytes = int(np.prod(p._data.shape)) * p._data.dtype.itemsize
            per_dev = nbytes // div_of(spec, p._data.shape)
            plan["params"] += per_dev
            plan["grads"] += per_dev
            sspec = _opt_state_spec(p, self.optimizer)
            for k, arr in (st or {}).items():
                s = self.optimizer.state_spec(p, k, arr, sspec)
                plan["opt_state"] += (int(np.prod(arr.shape))
                                      * jnp.dtype(arr.dtype).itemsize
                                      ) // div_of(s, arr.shape)
        plan["total"] = sum(plan.values())
        plan["axes"] = dict(axes)
        return plan

    def aot_memory_analysis(self, *batch):
        """Compile the full step ahead-of-time with ABSTRACT inputs (params,
        optimizer state, and batch as ShapeDtypeStructs — nothing is
        materialized or executed) and return XLA's buffer-assignment memory
        analysis: the compiler-accounted per-device argument/output/temp
        bytes, i.e. the true activation+workspace footprint of the chosen
        remat/pipeline schedule. `batch` leaves may be jax.ShapeDtypeStruct
        or arrays."""
        abstract_state = self._abstract_opt_state()
        saved = self._opt_state
        self._opt_state = abstract_state
        try:
            flat, treedef = jax.tree.flatten(tuple(
                b if isinstance(b, jax.ShapeDtypeStruct)
                else (b._data if isinstance(b, Tensor) else jnp.asarray(b))
                for b in batch))
            built = self._build(treedef, [len(a.shape) for a in flat])
            p_sds = tuple(jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)
                          for p in self._params)
            s_sds = tuple(abstract_state)
            key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
            sstate = None
            if self._scaler is not None:
                sstate = tuple(jax.ShapeDtypeStruct((), d)
                               for d in (jnp.float32, jnp.int32, jnp.int32))
            lowered = built.lower(
                p_sds, s_sds, sstate, jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.float32), key, *flat)
            return lowered.compile().memory_analysis()
        finally:
            self._opt_state = saved

    def _register_memz(self):
        """Register params/opt-state as HBM-ledger owners (ISSUE 18) —
        after the first compile, once opt state has materialized at its
        final (possibly cast) dtypes. Reader-backed: the ledger reads
        host-side nbytes metadata, never device values."""
        if self.memz is None or self._memz_registered:
            return
        self._memz_registered = True
        self.memz.register(
            "train_params",
            lambda: int(sum(p._data.nbytes for p in self._params)),
            kind="params", replace=True)
        self.memz.register(
            "train_opt_state",
            lambda: int(sum(getattr(leaf, "nbytes", 0)
                            for leaf in jax.tree.leaves(
                                self._opt_state or ()))),
            kind="opt_state", replace=True)
        self.memz.sample("train_params", "train_opt_state")
        if self.monitor is not None and getattr(self.monitor, "memz",
                                                None) is None:
            # per-record memory samples now read the ledger's host
            # counters instead of rationing live-array scans (r7 fix)
            self.monitor.memz = self.memz

    def _launch(self, compiled, *args):
        """Run one compiled launch; a device allocation failure dumps the
        OOM post-mortem (census + growth curve + the offending step)
        before re-raising — RESOURCE_EXHAUSTED leaves with a named
        owner attached."""
        try:
            return compiled(*args)
        except BaseException as e:
            if self.memz is not None:
                from ..obs.memz import looks_like_oom
                if looks_like_oom(e):
                    self.memz.post_mortem(
                        error=e,
                        context={"site": "train_step.launch",
                                 "step": self._step_i})
            raise

    def run_steps(self, n_steps: int, *stacked_batch):
        """Run `n_steps` steps from batches stacked on dim 0 ([n, ...] per
        leaf), one compiled launch. Returns the per-step losses Tensor."""
        tl = self.timeline if self.timeline is not None else _tl_current()
        tl_t0 = tl.now() if tl is not None else None
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()
            self._apply_param_shardings()
        arrays = _tree_unwrap(stacked_batch)
        flat, treedef = jax.tree.flatten(arrays)
        key_sig = ("scan", n_steps,
                   tuple((tuple(a.shape), str(a.dtype)) for a in flat))
        compiled = self._compiled.get((treedef, key_sig))
        was_compile = compiled is None
        if compiled is None:
            # lint audits the SINGLE-step pure function with per-step
            # batch slices — the scan wrapper adds only the loop carry
            self._maybe_lint(treedef, [
                jax.ShapeDtypeStruct(tuple(a.shape[1:]), a.dtype)
                for a in flat])
            # scan length is part of the kind: different n_steps is a
            # deliberately different executable (warmup vs timed runs),
            # not shape instability — only same-length re-traces count
            self._on_compile(f"train_step.run_steps[n={n_steps}]", key_sig)
            compiled = self._build_scan(treedef, n_steps)
            self._compiled[(treedef, key_sig)] = compiled
        self._register_memz()
        lr = jnp.float32(self.optimizer.get_lr())
        key = _random.split_key()
        if self.mesh is not None:
            flat = [self._to_global(a, P(None, *self.data_axes))
                    if a.ndim > 1 else a for a in flat]
        t0 = time.perf_counter() if self.monitor is not None else None
        losses, new_params, new_state, new_sstate, auxs = self._launch(
            compiled,
            tuple(p._data for p in self._params), tuple(self._opt_state),
            self._scaler_state_in(), jnp.int32(self._step_i + 1), lr, key,
            *flat)
        if self.monitor is not None:
            # launch wall time (includes waiting on the previous launch's
            # donated buffers — the steady-state device rate from the 2nd
            # launch on; fence with a host read for an exact figure)
            self.monitor.end_step(steps=n_steps,
                                  wall_s=time.perf_counter() - t0)
        tl_t1 = tl.now() if tl is not None else None
        self._step_i += n_steps
        if tl is not None:
            # the whole launch is one span: a cache-miss call is compile
            # badput (trace + XLA compile dominate), a steady call is
            # `step` goodput; `step` names the LAST step of the window
            tl.record("compile" if was_compile else "step", tl_t0, tl_t1,
                      step=self._step_i, steps=n_steps)
        for p, na in zip(self._params, new_params):
            p._data = na
            p._node = None
        self._opt_state = list(new_state)
        if self._numerics is not None:
            # the fetched stats (and hence any dump) describe the LAST step
            # of the launch — record that step's batch slice and the key the
            # scan actually used for it, so the dump replays that step
            self._last_batch_struct = jax.tree.map(lambda a: a[-1], arrays)
            self._last_key = jax.random.split(key, n_steps)[-1]
        # aux leaves are stacked [n_steps, ...]; keep the last step's view
        # (still device arrays — no sync)
        last_aux = jax.tree.map(lambda v: v[-1], auxs) if auxs else auxs
        self._after_step(losses, new_sstate, last_aux, steps=n_steps)
        self._post_step()
        return Tensor(losses)

    def __call__(self, *batch):
        tl = self.timeline if self.timeline is not None else _tl_current()
        tl_t0 = tl.now() if tl is not None else None
        if self._opt_state is None:
            self._opt_state = self._init_opt_state()
            self._apply_param_shardings()
        arrays = _tree_unwrap(batch)
        flat, treedef = jax.tree.flatten(arrays)
        key_sig = tuple((tuple(a.shape), str(a.dtype)) for a in flat)
        compiled = self._compiled.get((treedef, key_sig))
        was_compile = compiled is None
        if compiled is None:
            self._maybe_lint(treedef, flat)
            self._on_compile("train_step", key_sig)
            compiled = self._build(treedef, [a.ndim for a in flat])
            self._compiled[(treedef, key_sig)] = compiled
        self._register_memz()

        self._step_i += 1
        lr = jnp.float32(self.optimizer.get_lr())
        key = _random.split_key()
        if self.mesh is not None:
            flat = [self._to_global(a, P(*self.data_axes))
                    if a.ndim > 0 else a for a in flat]
        t0 = time.perf_counter() if self.monitor is not None else None
        loss, new_params, new_state, new_sstate, aux = self._launch(
            compiled,
            tuple(p._data for p in self._params), tuple(self._opt_state),
            self._scaler_state_in(), jnp.int32(self._step_i), lr, key, *flat)
        if self.monitor is not None:
            self.monitor.end_step(wall_s=time.perf_counter() - t0)
        if tl is not None:
            tl.record("compile" if was_compile else "step", tl_t0, tl.now(),
                      step=self._step_i)

        for p, na in zip(self._params, new_params):
            p._data = na
            p._node = None
        self._opt_state = list(new_state)
        self._last_batch_struct = arrays
        self._last_key = key
        self._after_step(loss, new_sstate, aux)
        self._post_step()
        if isinstance(self.optimizer._lr, object) and hasattr(self.optimizer._lr, "step") \
                and not isinstance(self.optimizer._lr, (int, float)):
            pass  # user drives scheduler.step() per reference convention
        return Tensor(loss)
