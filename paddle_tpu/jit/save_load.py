"""jit.save / jit.load (reference: python/paddle/jit/api.py save/load +
fluid/jit serializer).

The reference serializes a translated static Program plus params. TPU-native:
we persist (a) the model's state_dict and (b) a small manifest; on load we
return a TranslatedLayer that replays the original Layer class if importable,
else a pure state container. AOT-compiled executable export (XLA serialized
computation) is planned in the inference subsystem (paddle_tpu.inference).
"""
from __future__ import annotations

import importlib
import json
import os
import pickle

import numpy as np

from ..core.tensor import Tensor
from ..framework.io import save as _save_obj, load as _load_obj


def save(layer, path, input_spec=None, **configs):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = layer.state_dict() if hasattr(layer, "state_dict") else {}
    manifest = {
        "class_module": type(layer).__module__,
        "class_name": type(layer).__name__,
        "format": "paddle_tpu.jit.v1",
    }
    _save_obj({"state_dict": state, "manifest": manifest}, path + ".pdparams")
    with open(path + ".pdmodel.json", "w") as f:
        json.dump(manifest, f)
    if input_spec:
        _export_aot(layer, path, input_spec)


def _export_aot(layer, path, input_spec):
    """AOT artifact: trace layer.forward under the given specs and serialize
    the StableHLO module (+ .pdmeta), the same format as
    static.save_inference_model — consumable by paddle_tpu.inference
    (reference: jit.save produces the __model__ the AnalysisPredictor loads)."""
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export
    from ..core.tensor import Tensor
    from ..core import autograd
    from .api import _trace_guard, _swap_params, InputSpec

    params = [p for _, p in layer.named_parameters()]
    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()

    def serving(*inputs):
        with _trace_guard(), autograd.no_grad():
            out = layer(*[Tensor(i) for i in inputs])
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return tuple(o._data if isinstance(o, Tensor) else o for o in outs)

    specs = [s if isinstance(s, InputSpec) else InputSpec(*s) for s in input_spec]

    # dims declared None/-1 export as symbolic (shape-polymorphic) like
    # static.save_inference_model; fall back to concrete batch=1 only if
    # symbolic export fails for this model
    def _avals(symbolic):
        scope = jax_export.SymbolicScope() if symbolic else None
        out = []
        for i, s in enumerate(specs):
            decl = tuple(-1 if (d is None or (isinstance(d, int) and d < 0))
                         else int(d) for d in s.shape)
            if symbolic and any(d == -1 for d in decl):
                spec = ",".join(f"d{i}_{j}" if d == -1 else str(d)
                                for j, d in enumerate(decl))
                shape = jax_export.symbolic_shape(spec, scope=scope)
            else:
                shape = tuple(1 if d == -1 else d for d in decl)
            out.append(jax.ShapeDtypeStruct(shape, s.dtype))
        return out

    from ..static.io import _export_platforms
    exported = None
    for symbolic in (True, False):
        try:
            exported = jax_export.export(jax.jit(serving),
                                         platforms=_export_platforms())(*_avals(symbolic))
            break
        except Exception:
            continue
    if exported is None:
        exported = jax_export.export(jax.jit(serving))(*_avals(False))
    avals = _avals(False)  # concrete shapes for the metadata header
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    meta = {
        "feed_names": [s.name or f"x{i}" for i, s in enumerate(specs)],
        "feed_shapes": [list(a.shape) for a in avals],
        "feed_dtypes": [str(np.dtype(a.dtype)) for a in avals],
        "fetch_names": ["out_%d" % i
                        for i in range(len(jax.eval_shape(serving, *avals)))],
    }
    with open(path + ".pdmeta", "w") as f:
        json.dump(meta, f)
    if was_training and hasattr(layer, "train"):
        layer.train()


class TranslatedLayer:
    """Loaded model artifact (reference: fluid/dygraph/io.py TranslatedLayer)."""

    def __init__(self, state_dict, manifest, layer=None):
        self._state_dict = state_dict
        self._manifest = manifest
        self._layer = layer

    def state_dict(self):
        return self._state_dict

    def __call__(self, *args, **kwargs):
        if self._layer is None:
            raise RuntimeError(
                f"Model class {self._manifest.get('class_module')}."
                f"{self._manifest.get('class_name')} could not be re-imported; "
                "only state_dict() is available.")
        return self._layer(*args, **kwargs)


def load(path, **configs):
    blob = _load_obj(path + ".pdparams")
    state, manifest = blob["state_dict"], blob["manifest"]
    layer = None
    try:
        mod = importlib.import_module(manifest["class_module"])
        cls = getattr(mod, manifest["class_name"])
        # only auto-instantiate no-arg constructibles
        try:
            layer = cls()
            layer.set_state_dict(state)
        except TypeError:
            layer = None
    except Exception:
        layer = None
    return TranslatedLayer(state, manifest, layer)
