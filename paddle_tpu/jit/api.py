"""@to_static — trace-and-compile (reference: python/paddle/jit/api.py:222,
dy2static/program_translator.py:283 StaticFunction + ProgramCache).

The reference rewrites Python AST into a static Program executed by
InterpreterCore (run_program op). TPU-native: jax.jit IS the tracer/compiler —
we functionalize a Layer by swapping its Parameters' storage for tracers,
trace the Python forward once per input signature (cache keyed like
CacheKey: shapes/dtypes/training flag), and register the whole compiled
function as ONE tape op so eager `.backward()` differentiates through it
(jax.vjp of a jitted function stays compiled).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter, apply_op
from ..core import random as _random
from ..core import autograd
from ..core.dtype import convert_dtype

_trace_state = threading.local()

# process-wide compile-cache miss counter (StaticFunction + TrainStep feed
# it; profiler.StepMonitor reads the per-step delta)
_compile_cache_misses = [0]

# analysis.lint_capture sink: while set (a list), serving executables
# fetched through the models' compiled-runner caches are wrapped so each
# call records (kind, jitted_fn, abstract args) for GraphLint.check_calls
_lint_capture_sink = None


def _maybe_wrap_lint_capture(fn, kind):
    """Identity unless a lint_capture() context is active."""
    sink = _lint_capture_sink
    if sink is None:
        return fn

    def wrapper(*args, **kwargs):
        from ..analysis.lint import _capture_record
        _capture_record(sink, kind, fn, args, kwargs)
        return fn(*args, **kwargs)
    return wrapper


def compile_cache_misses() -> int:
    """Total jit compile-cache misses (new trace signatures) this process."""
    return _compile_cache_misses[0]


def _note_cache_miss():
    _compile_cache_misses[0] += 1


def _in_jit_trace() -> bool:
    return getattr(_trace_state, "depth", 0) > 0


@contextlib.contextmanager
def _trace_guard():
    _trace_state.depth = getattr(_trace_state, "depth", 0) + 1
    try:
        yield
    finally:
        _trace_state.depth -= 1


class InputSpec:
    """Reference: paddle.static.InputSpec (static/input.py)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


@contextlib.contextmanager
def _swap_params(params: List[Tensor], arrays):
    """Temporarily rebind Tensor storage to (traced) arrays."""
    saved = [p._data for p in params]
    saved_nodes = [p._node for p in params]
    for p, a in zip(params, arrays):
        p._data = a
        p._node = None
    try:
        yield
    finally:
        for p, s, n in zip(params, saved, saved_nodes):
            p._data = s
            p._node = n


def _tree_unwrap(obj):
    if isinstance(obj, Tensor):
        return obj._data
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_unwrap(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_unwrap(v) for k, v in obj.items()}
    return obj


def _tree_wrap(obj):
    if isinstance(obj, jax.Array):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_wrap(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_wrap(v) for k, v in obj.items()}
    return obj


def _collect_layers(fn):
    """Find Layer instances reachable from fn (bound self or closure)."""
    from ..nn.layer import Layer
    layers = []
    self_obj = getattr(fn, "__self__", None)
    if isinstance(self_obj, Layer):
        layers.append(self_obj)
    closure = getattr(fn, "__closure__", None)
    if closure:
        for cell in closure:
            try:
                v = cell.cell_contents
            except ValueError:
                continue
            if isinstance(v, Layer):
                layers.append(v)
    return layers


class StaticFunction:
    def __init__(self, function, input_spec=None, layer=None, **kwargs):
        # dy2static: rewrite data-dependent Python if/while into
        # lax.cond/while_loop convert_* calls (jit/dy2static.py). Falls back
        # to the original function when source is unavailable.
        from .dy2static import ast_transform
        self._original_fn = function
        self._fn = ast_transform(function)
        self._input_spec = input_spec
        self._layer = layer
        self._cache = {}
        self.__name__ = getattr(function, "__name__", "static_fn")

    @property
    def _layers(self):
        if self._layer is not None:
            return [self._layer]
        return _collect_layers(self._fn)

    def _params_and_buffers(self):
        params, buffers = [], []
        for layer in self._layers:
            for _, p in layer.named_parameters():
                params.append(p)
            for _, b in layer.named_buffers():
                buffers.append(b)
        return params, buffers

    def __call__(self, *args, **kwargs):
        from . import _to_static_enabled
        if not _to_static_enabled:
            return self._original_fn(*args, **kwargs)
        params, buffers = self._params_and_buffers()
        arg_arrays = _tree_unwrap(args)
        kw_arrays = _tree_unwrap(kwargs)
        flat_args, treedef = jax.tree.flatten((arg_arrays, kw_arrays))
        training = any(getattr(l, "training", False) for l in self._layers)
        key_shapes = tuple(
            (tuple(a.shape), str(a.dtype)) if hasattr(a, "shape") else repr(a)
            for a in flat_args)
        cache_key = (treedef, key_shapes, training, len(params), len(buffers))

        entry = self._cache.get(cache_key)
        if entry is None:
            _note_cache_miss()
            fn = self._fn
            out_treedef_box = []

            def pure(param_arrays, buffer_arrays, key, *flat):
                a_args, a_kwargs = jax.tree.unflatten(treedef, flat)
                with _trace_guard(), _swap_params(params + buffers,
                                                  list(param_arrays) + list(buffer_arrays)), \
                        _random.trace_key_scope(key), autograd.no_grad():
                    w_args = _tree_wrap(a_args)
                    w_kwargs = _tree_wrap(a_kwargs)
                    out = fn(*w_args, **w_kwargs)
                flat_out, out_treedef = jax.tree.flatten(_tree_unwrap(out))
                if not out_treedef_box:
                    out_treedef_box.append(out_treedef)
                return tuple(flat_out)

            entry = (jax.jit(pure), out_treedef_box)
            self._cache[cache_key] = entry
        jitted, out_treedef_box = entry

        key = _random.split_key()
        buffer_arrays = [b._data for b in buffers]

        # Register as one tape op: grads flow to params (and tensor args).
        def op_fn(*xs):
            p_arrays = xs[:len(params)]
            rest = xs[len(params):]
            return jitted(p_arrays, buffer_arrays, key, *rest)

        n_out_hint = None if not out_treedef_box else out_treedef_box[0].num_leaves
        out = apply_op(f"to_static[{self.__name__}]", op_fn,
                       list(params) + [a if isinstance(a, jax.Array) else jnp.asarray(a)
                                       for a in flat_args],
                       n_outputs=n_out_hint)
        leaves = list(out) if isinstance(out, tuple) else [out]
        structured = jax.tree.unflatten(out_treedef_box[0], leaves)
        return structured

    # reference-API compat
    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._original_fn)
        except (OSError, TypeError):
            return "<source unavailable>"


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    """Decorator: compile a function/Layer.forward with XLA
    (reference: paddle.jit.to_static, jit/api.py:222)."""
    from ..nn.layer import Layer

    def decorate(fn):
        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(layer.forward, input_spec, layer=layer)
            layer.forward = sf
            return layer
        return StaticFunction(fn, input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass
