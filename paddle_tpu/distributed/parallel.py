"""Parallel environment bootstrap.

Reference: python/paddle/distributed/parallel.py:108 init_parallel_env —
TCPStore rendezvous (parallel.py:279) + ProcessGroupNCCL creation. TPU-native:
`jax.distributed.initialize` is the coordination service (replaces TCPStore,
SURVEY §5.8), after which every host sees the full global device list and a
single logical mesh. On one host (or under the CPU virtual-device test
platform) no rendezvous is needed at all.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from . import mesh as _mesh


class ParallelEnv:
    """Reference: paddle.distributed.ParallelEnv (fluid/dygraph/parallel.py)."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_TPU_LOCAL_RANK", jax.process_index()))

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return self.local_rank


def init_parallel_env(mesh_axes: Optional[dict] = None):
    """Initialise the distributed runtime and the global mesh.

    Single-controller semantics: "world size" is the number of addressable
    devices (chips), not OS processes; on multi-host TPU each host runs the
    same program and jax.distributed stitches them into one world — the
    analog of the reference's trainer_id/trainer_endpoints env contract
    (parallel.py:146-214) with no sockets to manage.
    """
    coord = os.environ.get("PADDLE_TPU_COORDINATOR")
    nproc = int(os.environ.get("PADDLE_TPU_NUM_PROCESSES", "1"))
    # IMPORTANT: don't touch jax.devices()/process_count() before
    # initialize — any backend query initializes the runtime and makes a
    # later jax.distributed.initialize a no-op (the classic ordering trap)
    if coord and nproc > 1 and not _mesh._get("dist_initialized"):
        try:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=nproc,
                process_id=int(os.environ.get("PADDLE_TPU_PROCESS_ID", "0")))
            _mesh._state.dist_initialized = True
        except Exception as e:
            import warnings
            warnings.warn(f"jax.distributed.initialize failed: {e}; "
                          "continuing single-process")
    if _mesh.get_mesh() is None:
        axes = mesh_axes or {"dp": len(jax.devices())}
        _mesh.set_mesh(_mesh.build_mesh(axes))
    return ParallelEnv()


def get_rank(group=None) -> int:
    """Process (host) index. In single-controller SPMD every host runs the
    same logical rank-free program; this exists for launcher/API parity
    (reference: paddle.distributed.get_rank)."""
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    m = _mesh.get_mesh()
    if m is not None:
        return m.size
    return len(jax.devices())


def shard_identity() -> tuple:
    """(shard_id, world_size) of THIS process for per-shard telemetry
    (ISSUE 13): the stable name a shard's StepMonitor JSONL stream and
    its straggler gauges carry. Launcher env wins (spawn/launch set
    PADDLE_TPU_PROCESS_ID before jax initializes — reading
    jax.process_index() here would trigger backend init, the classic
    ordering trap init_parallel_env documents); an already-initialized
    multi-process runtime falls back to its process index."""
    pid = os.environ.get("PADDLE_TPU_PROCESS_ID",
                         os.environ.get("PADDLE_TRAINER_ID"))
    world = os.environ.get("PADDLE_TPU_NUM_PROCESSES",
                           os.environ.get("PADDLE_TRAINERS_NUM"))
    if pid is not None:
        return int(pid), int(world or 1)
    return jax.process_index(), jax.process_count()


def is_initialized() -> bool:
    return _mesh.get_mesh() is not None


def barrier(group=None):
    """Block until all *hosts* reach this point (reference: barrier op,
    operators/collective/barrier_op.cc). Single-host: a device drain is
    enough (one program order). Multi-host: a real cross-host sync via a
    tiny all-device collective."""
    import jax.numpy as jnp
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")
        return
    jax.block_until_ready(jax.device_put(jnp.ones((), jnp.int32)))
