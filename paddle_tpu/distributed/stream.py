"""paddle.distributed.stream — stream-variant collective API.

Reference (SURVEY §2.2): communication/stream/*.py — the same collectives
with `use_calc_stream` control for manual comm/compute overlap. On TPU
there are no user streams: XLA schedules collectives asynchronously
(start/done pairs) and overlaps them with compute on its own, so the
stream variants alias the plain ops; `sync_op`/`use_calc_stream` are
accepted and ignored (the reason they exist is solved by the compiler).
"""
from __future__ import annotations

from functools import wraps

from . import collective as _c


def _alias(fn):
    @wraps(fn)
    def inner(*args, sync_op=True, use_calc_stream=False, **kw):
        return fn(*args, **kw)
    return inner


all_reduce = _alias(_c.all_reduce)
all_gather = _alias(_c.all_gather)
reduce = _alias(_c.reduce)  # noqa: A001
reduce_scatter = _alias(_c.reduce_scatter)
broadcast = _alias(_c.broadcast)
alltoall = _alias(_c.alltoall)
scatter = _alias(_c.scatter) if hasattr(_c, "scatter") else None
send = _alias(_c.send) if hasattr(_c, "send") else None
recv = _alias(_c.recv) if hasattr(_c, "recv") else None
