"""Pipeline parallelism.

Reference: fleet/meta_parallel — PipelineLayer/LayerDesc segmentation
(pp_layers.py:209,57), 1F1B schedule (pipeline_parallel.py:117-228),
interleaved virtual stages (:461-761), P2P meta-exchange
(pp_utils/p2p_communication.py).

TPU-native design (SURVEY §7 "hard parts"): the reference's imperative
p2p + per-microbatch autograd does not map to XLA. Two mechanisms replace it:

1. **Collective pipeline** (`pipeline_scan`) — the production path for
   uniform repeated stages (transformer blocks): stage params are stacked on
   a leading dim sharded over the `pp` mesh axis; one `lax.scan` drives
   microbatches through the stages with `ppermute` rotating activations to
   the next stage each tick. The schedule is 1F1B-equivalent in steady state
   (each stage computes every tick; bubble = (S-1) ticks like 1F1B), and the
   whole thing is ONE compiled program XLA can overlap with ICI transfers.

2. **`PipelineParallel` wrapper** (`fleet.distributed_model` parity) — a
   micro-batched gradient-accumulation driver with the reference's
   train_batch(data, scaler) surface. Semantically GPipe: same gradients,
   deterministic; stage placement comes from the stacked-stage sharding when
   the model opts in, else the model runs whole.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from . import mesh as _mesh


class LayerDesc:
    """Reference: pp_layers.py:57 — deferred layer construction so each stage
    materialises only its own layers; here used for segmentation metadata."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Reference: pp_layers.py:77 — layers shared across stages (tied
    embeddings). Single-controller note: sharing is plain Python object
    sharing; the reference's allreduce_shared_weight_gradients is implicit."""

    def __init__(self, key, layer_cls, *args, forward_func=None, shared_weight_attr="weight",
                 **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer:
    """Reference: pp_layers.py:209 — builds stages from a layer list.

    TPU-native: all layers exist in the one controller; `seg_method`
    partitions them into `num_stages` segments only to derive stage ids for
    the collective pipeline / sharding annotations.
    """

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method: str = "uniform",
                 recompute_interval: int = 0, **kwargs):
        from ..nn.layer import Layer as NNLayer, Sequential
        built = []
        shared = {}
        for d in layers:
            if isinstance(d, SharedLayerDesc):
                if d.key not in shared:
                    shared[d.key] = d.build_layer()
                built.append(shared[d.key])
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            else:
                built.append(d)
        self.layers = built
        self.num_stages = num_stages or max(1, _mesh.mesh_axis_size("pp"))
        self.loss_fn = loss_fn
        self.recompute_interval = recompute_interval
        self._model = Sequential(*built)
        bounds = np.linspace(0, len(built), self.num_stages + 1).astype(int)
        self.stage_bounds = list(zip(bounds[:-1], bounds[1:]))

    def forward(self, x):
        for i, l in enumerate(self.layers):
            if self.recompute_interval and i % self.recompute_interval == 0:
                from .recompute import recompute
                x = recompute(l, x)
            else:
                x = l(x)
        return x

    __call__ = forward

    def parameters(self):
        return self._model.parameters()

    def named_parameters(self, *a, **k):
        return self._model.named_parameters(*a, **k)

    def named_buffers(self, *a, **k):
        return self._model.named_buffers(*a, **k)

    def state_dict(self, *a, **k):
        return self._model.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._model.set_state_dict(*a, **k)

    def train(self):
        self._model.train()
        return self

    def eval(self):
        self._model.eval()
        return self

    @property
    def training(self):
        return self._model.training


class PipelineParallel:
    """Reference: meta_parallel/pipeline_parallel.py:31 — train_batch driver.

    Gradient-accumulation schedule over `accumulate_steps` microbatches
    (GPipe-equivalent gradients; the compiled collective pipeline is the
    steady-state-1F1B perf path via `pipeline_scan`).
    """

    def __init__(self, model, hcg, strategy):
        self.model = model
        self.hcg = hcg
        self.strategy = strategy
        self.accumulate_steps = int(
            strategy.pipeline_configs.get("accumulate_steps", 1)) if strategy else 1
        self._loss_fn = getattr(model, "loss_fn", None)

    def __call__(self, *args, **kwargs):
        return self.model(*args, **kwargs)

    def parameters(self):
        return self.model.parameters()

    def state_dict(self, *a, **k):
        return self.model.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self.model.set_state_dict(*a, **k)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Reference: pipeline_parallel.py:228 — returns the mean loss."""
        x, y = data
        n = self.accumulate_steps
        xb = _split_micro(x, n)
        yb = _split_micro(y, n)
        total = 0.0
        for mx, my in zip(xb, yb):
            out = self.model(mx)
            loss = self._loss_fn(out, my) if self._loss_fn else out
            if hasattr(loss, "mean"):
                loss = loss.mean()
            scaled = loss / float(n)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total += float(loss)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(jnp.asarray(total / n))


def _split_micro(t, n):
    arr = t._data if isinstance(t, Tensor) else jnp.asarray(t)
    return [Tensor(a) for a in jnp.split(arr, n, axis=0)]


class CompiledPipelineParallel(PipelineParallel):
    """Pipeline engine for stacked-stage models (models/gpt_stacked.py):
    train_batch compiles ONE fused step whose loss internally runs the
    `pipeline_spmd` microbatch schedule over the pp mesh axis — the compiled
    replacement for the reference's eager 1F1B driver loop
    (pipeline_parallel.py:117-228). Requires the model to expose
    `loss(inputs, labels, num_microbatches=...)`.

    strategy.pipeline_configs["interleave"] > 1 routes through the
    interleaved virtual-stage schedule (pipeline_scan_interleaved; the
    reference's PipelineParallelWithInterleave production mode,
    pipeline_parallel.py:461-761) — the model's loss() must accept
    num_virtual (models/gpt_stacked.py does)."""

    def __init__(self, model, hcg, strategy):
        super().__init__(model, hcg, strategy)
        self._train_step = None
        self._step_optimizer = None
        self.num_virtual = int(
            strategy.pipeline_configs.get("interleave", 1)) if strategy else 1

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        if scaler is not None:
            # The eager fallback drives loss via model.loss_fn(out, y), which
            # stacked models don't define (their loss() consumes input ids) —
            # delegating would silently optimize mean(logits). fp16 loss
            # scaling is also unnecessary on the bf16-native compiled path.
            raise ValueError(
                "CompiledPipelineParallel.train_batch does not take a "
                "GradScaler: the compiled pp path trains in bf16/fp32 and "
                "needs no loss scaling (use amp.debugging.check_numerics "
                "for overflow checks). Drop the scaler argument.")
        x, y = data
        if self._train_step is None or self._step_optimizer is not optimizer:
            from ..jit.train_step import TrainStep
            n = max(1, self.accumulate_steps)
            v = max(1, self.num_virtual)
            kw = {"num_virtual": v} if v > 1 else {}
            mesh = getattr(self.hcg, "mesh", None) or _mesh.get_mesh()
            self._train_step = TrainStep(
                self.model, optimizer,
                lambda ids, lbl: self.model.loss(ids, lbl,
                                                 num_microbatches=n, **kw),
                mesh=mesh, data_axes=("dp",))
            self._step_optimizer = optimizer
        loss = self._train_step(x, y)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss


# ---------------------------------------------------------------------------
# Auto-sharding pipeline: the production path for hybrid dp×pp×mp models.
# ---------------------------------------------------------------------------

def pipeline_spmd(stage_fn: Callable, stacked_params, x_microbatches,
                  axis: str = "pp", num_stages: Optional[int] = None,
                  remat: bool = True):
    """Pipeline microbatches through S stages in pjit "auto" mode.

    Unlike `pipeline_scan` (a shard_map kernel over ONLY the pp axis, which
    replicates all other mesh axes inside its body), this formulation stays
    in the compiler's auto-sharding world so the stage body composes with
    dp/mp/sp sharding constraints — the requirement for hybrid dp×pp×mp
    flagship training (reference capability: 4-D HybridCommunicateGroup,
    fleet/base/topology.py:53).

    Mechanics: all S stages compute every tick, batched over a leading stage
    dim sharded P(axis); `jnp.roll` on that dim rotates activations to the
    next stage, which XLA lowers to a collective-permute over the pp axis —
    the compiled analog of the reference's send_forward/recv_forward p2p
    (pp_utils/p2p_communication.py:516-641). Tick t: stage s holds microbatch
    t - s; after M + S - 1 ticks all M microbatches have left the last stage.
    The schedule is 1F1B-like in steady state (every stage busy every tick,
    bubble fraction (S-1)/(M+S-1)); XLA overlaps the permute with compute.

    stage_fn(stacked_params, acts) -> acts maps [S, mb, ...] -> [S, mb, ...]
    applying each stage's own depth slice (leaves of `stacked_params` have
    leading dim S, sharded P(axis) via param pspecs).

    With `remat`, each tick's stage compute is rematerialised in the
    backward pass (jax.checkpoint), bounding live activations at
    O(ticks × microbatch) like the reference's recompute+pipeline combo.
    """
    S = num_stages or _mesh.mesh_axis_size(axis)
    M = x_microbatches.shape[0]
    T = M + S - 1

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def tick(carry, t):
        buf, outs = carry
        # stage 0 consumes microbatch t (clipped reads are masked out by the
        # collection guard below; dim 0 of buf is the stage dim)
        buf = buf.at[0].set(x_microbatches[jnp.clip(t, 0, M - 1)])
        buf = _shard_stagewise(buf, axis)
        acts = fn(stacked_params, buf)
        acts = _shard_stagewise(acts, axis)
        # microbatch leaving the last stage at tick t is t - (S - 1)
        done = t - (S - 1)
        outs = lax.cond(
            done >= 0,
            lambda o: o.at[jnp.clip(done, 0, M - 1)].set(acts[S - 1]),
            lambda o: o, outs)
        buf = jnp.roll(acts, 1, axis=0)   # ppermute over the pp axis
        return (buf, outs), None

    buf0 = jnp.zeros((S,) + x_microbatches.shape[1:], x_microbatches.dtype)
    outs0 = jnp.zeros_like(x_microbatches)
    # tick counters stay s32: with x64 on, an s64 scatter index reaches the
    # transpose-of-dynamic_update_slice as s64 while SPMD partitioning emits
    # s32 offsets — the HLO verifier rejects the mixed compare
    (buf, outs), _ = lax.scan(tick, (_shard_stagewise(buf0, axis), outs0),
                              jnp.arange(T, dtype=jnp.int32))
    return outs


def _shard_stagewise(a, axis):
    """Pin the leading stage dim of an activation buffer to the pp axis."""
    return _mesh.shard_constraint(a, axis, "dp", *([None] * (a.ndim - 2)))


# ---------------------------------------------------------------------------
# Collective pipeline: scan + ppermute over the pp axis (the compiled path)
# ---------------------------------------------------------------------------

def pipeline_scan(stage_fn: Callable, stacked_params, x_microbatches,
                  axis: str = "pp", num_stages: Optional[int] = None):
    """Run microbatches through S identical stages pipelined over mesh axis.

    stage_fn(params_for_stage, activation) -> activation, where
    `stacked_params` is a pytree whose leaves have leading dim S (sharded
    P(axis) by the caller's pjit specs) and `x_microbatches` has leading dim M.

    Inside shard_map each device holds ONE stage's params [1, ...]; the loop
    runs M + S - 1 ticks; tick t: stage s processes microbatch t - s. The
    activation ring rotates via ppermute (the TPU analog of the reference's
    send_forward/recv_forward p2p, p2p_communication.py:516-641).

    Returns outputs stacked [M, ...] (from the last stage, broadcast).
    """
    S = num_stages or _mesh.mesh_axis_size(axis)
    M = x_microbatches.shape[0]

    def per_stage(params, xs):  # runs per-device under shard_map
        params = jax.tree.map(lambda a: a[0], params)  # [1,...] -> [...]
        sid = lax.axis_index(axis)
        T = M + S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]
        buf = jnp.zeros_like(xs[0])
        outs = jnp.zeros((M,) + xs.shape[1:], xs.dtype)

        def tick(carry, t):
            buf, outs = carry
            mb_idx = jnp.clip(t, 0, M - 1)
            inp = jnp.where(sid == 0, xs[mb_idx], buf)
            act = stage_fn(params, inp)
            # stage S-1's finished microbatch index at tick t is t-(S-1)
            done_idx = t - (S - 1)
            is_done = jnp.logical_and(sid == S - 1, done_idx >= 0)
            outs = lax.cond(
                is_done,
                lambda o: o.at[jnp.clip(done_idx, 0, M - 1)].set(act),
                lambda o: o, outs)
            buf = lax.ppermute(act, axis, perm)
            return (buf, outs), None

        (buf, outs), _ = lax.scan(tick, (buf, outs), jnp.arange(T, dtype=jnp.int32))
        # broadcast final outputs from last stage to all (so out_specs can
        # be replicated); psum of one-hot contribution
        contrib = jnp.where(sid == S - 1, outs, jnp.zeros_like(outs))
        return lax.psum(contrib, axis)

    mesh = _mesh.get_mesh()
    from jax import shard_map
    pspec = jax.tree.map(lambda _: P(axis), stacked_params)
    f = shard_map(per_stage, mesh=mesh,
                  in_specs=(pspec, P()), out_specs=P(),
                  check_vma=False)
    return f(stacked_params, x_microbatches)


def interleaved_ticks(M: int, S: int, V: int) -> int:
    """Tick count of `pipeline_scan_interleaved` (one CHUNK of compute per
    device per tick). Microbatch m is injected at tick (m%S) + S·V·(m//S)
    and drains S·V ticks later; for M = k·S this is M·V + S - 1 — the
    interleaved-1F1B fill/drain cost (Megatron: bubble shrinks by 1/V).
    The plain schedule costs (M+S-1) ticks of V chunks each = V·(M+S-1)
    chunk-times, strictly more for V>1: interleaving trades more, smaller
    p2p messages for a shorter pipeline fill — the same trade the
    reference's PipelineParallelWithInterleave makes."""
    L = S * V
    return (M - 1) % S + L * ((M - 1) // S) + L


def pipeline_scan_interleaved(stage_fn: Callable, stacked_params,
                              x_microbatches, axis: str = "pp",
                              num_virtual: int = 2):
    """Interleaved virtual-stage pipeline (reference:
    PipelineParallelWithInterleave, pipeline_parallel.py:461-761).

    The model's L = S·V logical stages are dealt round-robin: device d owns
    virtual chunks {v·S + d}. Each tick every device computes ONE chunk —
    1/V of a plain-schedule tick — and the ring advances one logical stage
    via ppermute. A microbatch therefore reaches the next device after one
    CHUNK (L/(S·V) of the model), not one full stage slice: the pipeline
    fill costs (S-1) chunk-times instead of (S-1) stage-times, the
    interleaved-1F1B bubble reduction. Total cost `interleaved_ticks(M,S,V)`
    = M·V + S - 1 chunk-times (M = k·S) vs the plain scan's V·(M+S-1).

    The ring carries (activation, logical_stage, microbatch_id) per device;
    device 0 injects a fresh microbatch whenever the arriving slot is free
    (finished microbatches leave the ring at device S-1). Manual collectives
    run only over `axis` (shard_map axis_names={axis}), so dp/mp shardings
    inside stage_fn stay in XLA's auto-sharding world — this kernel composes
    with hybrid dp×pp×mp meshes, unlike a fully-manual shard_map.

    `stacked_params` leaves have leading dim L = S·num_virtual, ordered so
    that P(axis) sharding hands device d rows [d·V, (d+1)·V) = its chunks
    v·S+d in chunk order (the caller permutes: row d·V+v = logical v·S+d).
    Returns outputs stacked [M, ...].
    """
    S = _mesh.mesh_axis_size(axis)
    V = num_virtual
    L = S * V
    M = x_microbatches.shape[0]
    T = interleaved_ticks(M, S, V)

    def per_device(params, xs):
        # params leaves: [V, ...] — this device's chunks; the chunk of an
        # arriving activation at logical stage l is l // S (l % S == sid is
        # a ring invariant: injection at stage 0 on device 0, +1 per hop)
        sid = lax.axis_index(axis)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            act, stage, mb, inj, outs = carry
            # device 0 injects into a free arriving slot (stage < 0)
            do_inj = (sid == 0) & (stage < 0) & (inj < M)
            act = jnp.where(do_inj, xs[jnp.clip(inj, 0, M - 1)], act)
            stage = jnp.where(do_inj, jnp.int32(0), stage)
            mb = jnp.where(do_inj, inj, mb)
            inj = inj + do_inj.astype(jnp.int32)
            # ONE chunk of compute (empty slots compute garbage and mask —
            # the static-shape XLA idiom for an idle tick)
            v = jnp.clip(stage // S, 0, V - 1)
            pv = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, v, 0, keepdims=False),
                params)
            occupied = stage >= 0
            act = jnp.where(occupied, stage_fn(pv, act), act)
            stage = jnp.where(occupied, stage + 1, stage)
            # finished microbatches leave the ring at device S-1 (= (L-1)%S)
            done = occupied & (stage == L)
            outs = lax.cond(
                done,
                lambda o: o.at[jnp.clip(mb, 0, M - 1)].set(act),
                lambda o: o, outs)
            stage = jnp.where(done, jnp.int32(-1), stage)
            act = lax.ppermute(act, axis, perm)
            stage = lax.ppermute(stage, axis, perm)
            mb = lax.ppermute(mb, axis, perm)
            return (act, stage, mb, inj, outs), None

        init = (jnp.zeros(xs.shape[1:], xs.dtype), jnp.int32(-1),
                jnp.int32(-1), jnp.int32(0), jnp.zeros_like(xs))
        (_, _, _, _, outs), _ = lax.scan(tick, init, jnp.arange(T, dtype=jnp.int32))
        contrib = jnp.where(sid == S - 1, outs, jnp.zeros_like(outs))
        return lax.psum(contrib, axis)

    mesh = _mesh.get_mesh()
    from jax import shard_map
    pspec = jax.tree.map(lambda _: P(axis), stacked_params)
    f = shard_map(per_device, mesh=mesh, axis_names={axis},
                  in_specs=(pspec, P()), out_specs=P(),
                  check_vma=False)
    # partial-manual shard_map (manual pp, auto dp/mp) only lowers inside a
    # jit scope — a bare eager call (and a bare jax.vjp trace, which the
    # eager tape uses) rejects it at construction. The jit wrapper is a
    # fresh closure per call, so the EAGER path recompiles each loss();
    # acceptable for tests/interactive use — production runs inside the one
    # fused TrainStep program, where this jit is traced inline.
    return jax.jit(f)(stacked_params, x_microbatches)
