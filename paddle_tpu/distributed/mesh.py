"""Global device-mesh runtime — the substrate of all parallelism.

TPU-native replacement for the reference's process-group world
(paddle/fluid/distributed/collective/process_group.h:53 + NCCL comm caches,
process_group_nccl.cc:573): instead of N processes bootstrapping NCCL
communicators through a TCPStore, a single controller owns a
`jax.sharding.Mesh` whose named axes ARE the communicator groups. Every
"process group" of the reference maps to a mesh axis; every collective maps
to an XLA collective over that axis riding ICI (SURVEY §5.8 TPU-equivalent).

Axis-name conventions (mirrors fleet's 4D hybrid topology order,
fleet/base/topology.py:53, extended with sp/ep which the reference lacks):
  dp  — data parallel            (reference: dp degree)
  pp  — pipeline stages          (reference: pp degree)
  sdp — sharded data parallel    (reference: sharding degree, ZeRO)
  mp  — tensor/model parallel    (reference: mp degree)
  sp  — sequence/context parallel (exceeds reference; SURVEY §5.7)
  ep  — expert parallel          (reference: MoE global_scatter groups)
"""
from __future__ import annotations

import contextlib
import types
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# process-global (NOT thread-local: DataLoader worker threads and the main
# thread must see the same mesh; fleet.init happens once per process)
_state = types.SimpleNamespace()

HYBRID_AXES = ("dp", "pp", "sdp", "mp")  # reference 4D order (topology.py:53)


def _get(name, default=None):
    return getattr(_state, name, default)


def build_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh from {axis_name: degree}; degrees must multiply to ndev.

    Axis order in `axes` is the physical layout order: the LAST axis varies
    fastest over adjacent devices, so put the heaviest-communication axis
    (mp/sp) last to keep its collectives on nearest-neighbour ICI — same
    logic as the reference giving mp the fastest-varying ranks
    (fleet/base/topology.py hybrid order).
    """
    if devices is None:
        devices = jax.devices()
    shape = tuple(axes.values())
    n = int(np.prod(shape)) if shape else 1
    if n != len(devices):
        raise ValueError(
            f"mesh axes {axes} require {n} devices, have {len(devices)}")
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(axes.keys()))


def set_mesh(mesh: Optional[Mesh]):
    _state.mesh = mesh


def get_mesh() -> Optional[Mesh]:
    """The process-global mesh (None until init_parallel_env/fleet.init)."""
    return _get("mesh")


def mesh_axis_size(axis: str) -> int:
    m = get_mesh()
    if m is None or axis not in m.axis_names:
        return 1
    return m.shape[axis]


def filter_spec(*entries):
    """PartitionSpec with axis names not present in the active mesh replaced
    by None — lets model code write its full sharding intent (dp/mp/sp/...)
    once and degrade gracefully on smaller meshes."""
    m = get_mesh()
    names = set(m.axis_names) if m is not None else set()

    def keep(e):
        if e is None:
            return None
        if isinstance(e, (tuple, list)):
            kept = tuple(x for x in e if x in names)
            return kept if kept else None
        return e if e in names else None

    return P(*[keep(e) for e in entries])


@contextlib.contextmanager
def mesh_scope(mesh: Mesh):
    prev = get_mesh()
    set_mesh(mesh)
    try:
        yield mesh
    finally:
        set_mesh(prev)


def named_sharding(*spec) -> Optional[NamedSharding]:
    m = get_mesh()
    if m is None:
        return None
    return NamedSharding(m, P(*spec))


def shard_constraint(arr, *spec):
    """with_sharding_constraint if a mesh is active and we are inside a
    trace; no-op otherwise. Used by parallel layers to pin activation
    layouts (the declarative analog of the reference's explicit
    _c_identity/_mp_allreduce calls in mpu/mp_ops.py:27-219)."""
    m = get_mesh()
    if m is None:
        return arr
    try:
        return jax.lax.with_sharding_constraint(arr, NamedSharding(m, filter_spec(*spec)))
    except (ValueError, TypeError):
        return arr
