"""Group sharding — ZeRO stages 1/2/3.

Reference: dygraph group-sharded stack — GroupShardedOptimizerStage2
(group_sharded_optimizer_stage2.py:53, shards optimizer states),
GroupShardedStage2 (grad sharding, group_sharded_stage2.py:46),
GroupShardedStage3 (param sharding with gather-on-use forward,
group_sharded_stage3.py:60), public API group_sharded_parallel
(distributed/sharding/group_sharded.py:37).

TPU-native: ZeRO is a *sharding annotation*, not a runtime (SURVEY §7 design
mapping). Over the `sdp` mesh axis:
  stage 1 ("os")     — optimizer state PartitionSpecs gain the sdp axis;
  stage 2 ("os_g")   — + gradients: XLA emits reduce-scatter instead of
                        all-reduce because the consumer (opt state) is sharded;
  stage 3 ("p_g_os") — + parameter specs gain the sdp axis; XLA emits the
                        gather-on-use all-gathers the reference implements by
                        rewriting layer forwards.
Same memory math, zero bespoke machinery: the TrainStep pjit does it all.
"""
from __future__ import annotations

from typing import Optional

from jax.sharding import PartitionSpec as P

from . import mesh as _mesh

_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def _with_axis(spec: Optional[P], shape, axis: str, size: int) -> P:
    """Add `axis` to the first dim that is free (spec None) and divisible."""
    entries = list(spec) if spec is not None else []
    entries += [None] * (len(shape) - len(entries))
    used = any((axis == e) or (isinstance(e, (tuple, list)) and axis in e)
               for e in entries)
    if used:
        return P(*entries)
    for i, (e, d) in enumerate(zip(entries, shape)):
        if e is None and d % size == 0 and d >= size:
            entries[i] = axis
            return P(*entries)
    return P(*entries)  # too small to shard — stays replicated (like the
    # reference keeping small params whole in a rank's shard bucket)


def shard_parameter_specs(model, axis: str = "sdp"):
    """Stage-3 annotation: shard every trainable param over `axis`."""
    size = _mesh.mesh_axis_size(axis)
    if size <= 1:
        return model
    for p in model.parameters():
        if not p.stop_gradient:
            p.pspec = _with_axis(p.pspec, p.shape, axis, size)
    return model


def shard_optimizer_state(optimizer, stage: int = 1, axis: str = "sdp"):
    """Stages 1/2: mark the optimizer so TrainStep shards its state pytree
    over `axis` (reference: GroupShardedOptimizerStage2 param2rank maps)."""
    optimizer._sharding_stage = stage
    optimizer._sharding_axis = axis
    return optimizer


def group_sharded_parallel(model, optimizer, level: str = "os_g", scaler=None,
                           group=None, offload: bool = False, sync_buffers: bool = False,
                           buffer_max_size: int = 0, segment_size: int = 0,
                           sync_comm: bool = False):
    """Reference: distributed/sharding/group_sharded.py:37 — same signature,
    returns (model, optimizer, scaler)."""
    stage = _LEVELS.get(level)
    if stage is None:
        raise ValueError(f"level must be one of {list(_LEVELS)}, got {level!r}")
    axis = "sdp" if _mesh.mesh_axis_size("sdp") > 1 else "dp"
    shard_optimizer_state(optimizer, stage=stage, axis=axis)
    if stage >= 3:
        shard_parameter_specs(model, axis=axis)
    return model, optimizer, scaler
