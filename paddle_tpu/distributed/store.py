"""TCPStore — rendezvous key-value store for multi-host bootstrap.

Reference: paddle/phi/core/distributed/store/tcp_store.cc (MasterDaemon :45,
TCPStore client :117), used by init_parallel_env (parallel.py:279) to
exchange comm ids. On TPU the *collective* bootstrap is jax.distributed's
coordination service (SURVEY §5.8) — this store exists for the
orchestration layer: the launch CLI's node sign-in, elastic heartbeats, and
user-level barriers (the role HTTPMaster/ETCDMaster play in
launch/controllers/master.py:65,177).

Wire protocol: newline-delimited UTF-8 — `CMD key [value]\n` → `OK [value]`.
Commands: SET/GET/ADD/WAIT/DEL/KEYS/PING. WAIT blocks until the key exists
(long-poll server side), the analog of tcp_store's wait().
"""
from __future__ import annotations

import os
import socket
import socketserver
import threading
import time
from typing import Optional


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        store = self.server._kv
        cond = self.server._cond
        while True:
            line = self.rfile.readline()
            if not line:
                return
            parts = line.decode("utf-8").rstrip("\n").split(" ", 2)
            cmd = parts[0].upper()
            try:
                if cmd == "SET":
                    key, val = parts[1], parts[2] if len(parts) > 2 else ""
                    with cond:
                        store[key] = val
                        cond.notify_all()
                    self._reply("OK")
                elif cmd == "GET":
                    with cond:
                        val = store.get(parts[1])
                    self._reply("OK " + val if val is not None else "MISSING")
                elif cmd == "ADD":
                    key, n = parts[1], int(parts[2]) if len(parts) > 2 else 1
                    with cond:
                        cur = int(store.get(key, "0")) + n
                        store[key] = str(cur)
                        cond.notify_all()
                    self._reply(f"OK {cur}")
                elif cmd == "WAIT":
                    key = parts[1]
                    timeout = float(parts[2]) if len(parts) > 2 else 300.0
                    deadline = time.time() + timeout
                    with cond:
                        while key not in store:
                            remaining = deadline - time.time()
                            if remaining <= 0 or not cond.wait(min(remaining, 1.0)):
                                if time.time() >= deadline:
                                    break
                        ok = key in store
                    self._reply("OK " + store[key] if ok else "TIMEOUT")
                elif cmd == "DEL":
                    with cond:
                        store.pop(parts[1], None)
                        cond.notify_all()
                    self._reply("OK")
                elif cmd == "KEYS":
                    prefix = parts[1] if len(parts) > 1 else ""
                    with cond:
                        keys = [k for k in store if k.startswith(prefix)]
                    self._reply("OK " + ",".join(keys))
                elif cmd == "PING":
                    self._reply("OK PONG")
                else:
                    self._reply("ERR unknown")
            except (BrokenPipeError, ConnectionResetError):
                return
            except Exception as e:  # keep the daemon alive on bad input
                try:
                    self._reply(f"ERR {type(e).__name__}")
                except OSError:
                    return

    def _reply(self, s: str):
        self.wfile.write((s + "\n").encode("utf-8"))
        self.wfile.flush()


class MasterDaemon:
    """The store server (reference: tcp_store.h:45 MasterDaemon — native
    C++ there, native C++ here: native/src/store.cc, a poll(2) event loop
    serving the same wire protocol GIL-free). Falls back to the in-process
    Python ThreadingTCPServer when no toolchain is available."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 use_native: bool = True):
        self._server = None
        self._native_id = None
        if use_native:
            try:
                from ..io.native import load_native
                lib = load_native()
            except Exception:
                lib = None
            if lib is not None:
                import ctypes
                out_port = ctypes.c_int(0)
                sid = lib.pt_store_start(host.encode(), int(port),
                                         ctypes.byref(out_port))
                if sid >= 0:
                    self._native_id = sid
                    self._native_lib = lib
                    self.port = out_port.value
                    return
        socketserver.ThreadingTCPServer.allow_reuse_address = True
        # handler threads must not block interpreter shutdown: a client that
        # never disconnects (or a long-poll WAIT) would otherwise hang the
        # process at exit
        socketserver.ThreadingTCPServer.daemon_threads = True
        self._server = socketserver.ThreadingTCPServer((host, port), _Handler)
        self._server._kv = {}
        self._server._cond = threading.Condition()
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def is_native(self) -> bool:
        return self._native_id is not None

    def stop(self):
        if self._native_id is not None:
            self._native_lib.pt_store_stop(self._native_id)
            self._native_id = None
            return
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class TCPStore:
    """Client (reference: tcp_store.h:117). `is_master=True` spawns the
    daemon in-process, matching `core.TCPStore(host, port, is_master, size)`
    as used by init_parallel_env (parallel.py:279)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 300.0):
        self._daemon = None
        if is_master:
            self._daemon = MasterDaemon(port=port)
            port = self._daemon.port
            host = "127.0.0.1" if host in ("0.0.0.0", "") else host
        self.host, self.port = host, port
        self.world_size = world_size
        self.timeout = timeout
        self._sock = None
        self._lock = threading.Lock()
        self._connect()

    def _connect(self):
        deadline = time.time() + self.timeout
        last = None
        while time.time() < deadline:
            try:
                self._sock = socket.create_connection((self.host, self.port),
                                                      timeout=self.timeout)
                self._f = self._sock.makefile("rwb")
                return
            except OSError as e:
                last = e
                time.sleep(0.2)
        raise TimeoutError(f"TCPStore connect to {self.host}:{self.port}: {last}")

    def _cmd(self, line: str) -> str:
        with self._lock:
            self._f.write((line + "\n").encode("utf-8"))
            self._f.flush()
            resp = self._f.readline().decode("utf-8").rstrip("\n")
        if resp.startswith("ERR"):
            raise RuntimeError(f"TCPStore: {resp}")
        return resp

    def set(self, key: str, value: str):
        self._cmd(f"SET {key} {value}")

    def get(self, key: str) -> Optional[str]:
        resp = self._cmd(f"GET {key}")
        return resp[3:] if resp.startswith("OK ") else (
            "" if resp == "OK" else None)

    def add(self, key: str, n: int = 1) -> int:
        return int(self._cmd(f"ADD {key} {n}").split(" ", 1)[1])

    def wait(self, key: str, timeout: Optional[float] = None) -> str:
        resp = self._cmd(f"WAIT {key} {timeout or self.timeout}")
        if resp == "TIMEOUT":
            raise TimeoutError(f"TCPStore.wait({key!r})")
        return resp[3:] if resp.startswith("OK ") else ""

    def delete(self, key: str):
        self._cmd(f"DEL {key}")

    def keys(self, prefix: str = "") -> list:
        resp = self._cmd(f"KEYS {prefix}")
        body = resp[3:] if resp.startswith("OK ") else ""
        return [k for k in body.split(",") if k]

    def barrier(self, name: str, world_size: Optional[int] = None,
                timeout: Optional[float] = None):
        """All `world_size` participants block until everyone arrives."""
        n = world_size or self.world_size
        arrived = self.add(f"__barrier__/{name}", 1)
        if arrived >= n:
            self.set(f"__barrier_done__/{name}", "1")
        self.wait(f"__barrier_done__/{name}", timeout)

    def close(self):
        try:
            if self._sock:
                self._sock.close()
        finally:
            if self._daemon:
                self._daemon.stop()
