"""Hybrid-parallel topology over the device mesh.

Reference: python/paddle/distributed/fleet/base/topology.py —
`CommunicateTopology` (:53) builds the dp×pp×sharding×mp rank hypercube and
`HybridCommunicateGroup` (:139) carves communication subgroups out of it.
TPU-native: the hypercube IS a jax Mesh; a "subgroup" is a mesh axis, so the
whole class reduces to bookkeeping over axis names — no communicator setup,
no rank enumeration. Extended with `sp` (sequence parallel) and `ep` (expert
parallel) axes the reference lacks (SURVEY §5.7).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax
from jax.sharding import Mesh

from . import mesh as _mesh
from .collective import Group


class CommunicateTopology:
    """Axis-name/degree bookkeeping (reference topology.py:53)."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._names = list(hybrid_group_names)
        self._dims = list(dims)

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(np.prod(self._dims))


_CANON = {"data": "dp", "pipe": "pp", "sharding": "sdp", "model": "mp",
          "sequence": "sp", "expert": "ep"}


class HybridCommunicateGroup:
    """Reference: topology.py:139. Maps each parallel dimension to a mesh
    axis and hands out Groups (= axes) instead of NCCL communicators."""

    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 mesh: Optional[Mesh] = None):
        self._topo = topology or CommunicateTopology()
        dims = dict(zip(self._topo.get_hybrid_group_names(), self._topo._dims))
        self._degrees = {_CANON.get(k, k): v for k, v in dims.items()}
        if mesh is None:
            axes = {ax: d for ax, d in self._degrees.items() if d > 1} or {"dp": 1}
            mesh = _mesh.build_mesh(axes)
        self._mesh = mesh
        _mesh.set_mesh(mesh)

    # degrees ---------------------------------------------------------------
    @property
    def mesh(self):
        return self._mesh

    def _deg(self, ax):
        return self._mesh.shape[ax] if ax in self._mesh.axis_names else 1

    def get_data_parallel_world_size(self):
        return self._deg("dp")

    def get_model_parallel_world_size(self):
        return self._deg("mp")

    def get_pipe_parallel_world_size(self):
        return self._deg("pp")

    def get_sharding_parallel_world_size(self):
        return self._deg("sdp")

    def get_sequence_parallel_world_size(self):
        return self._deg("sp")

    def get_expert_parallel_world_size(self):
        return self._deg("ep")

    # ranks: single-controller SPMD has no per-process rank; these exist for
    # API parity and return 0 / in-trace axis_index where meaningful.
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    # groups ----------------------------------------------------------------
    def _group(self, ax) -> Optional[Group]:
        if ax not in self._mesh.axis_names:
            return None
        return Group(self._mesh, ax)

    def get_data_parallel_group(self):
        return self._group("dp")

    def get_model_parallel_group(self):
        return self._group("mp")

    def get_pipe_parallel_group(self):
        return self._group("pp")

    def get_sharding_parallel_group(self):
        return self._group("sdp")

    def get_sequence_parallel_group(self):
        return self._group("sp")

    def get_expert_parallel_group(self):
        return self._group("ep")

    def get_check_parallel_group(self):
        return None

    def get_parallel_mode(self):
        """Reference: topology.py — returns the dominant mode for
        fleet.distributed_model dispatch (fleet/model.py:135-160)."""
        if self._deg("pp") > 1:
            return "pipeline"
        if self._deg("sdp") > 1:
            return "sharding"
        if self._deg("mp") > 1 or self._deg("sp") > 1:
            return "model"
        return "data"

    def topology(self):
        return self._topo
