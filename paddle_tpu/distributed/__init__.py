"""paddle_tpu.distributed — parallelism over TPU device meshes.

Reference surface: python/paddle/distributed/ (SURVEY §2.2) — collective
communication API, fleet facade, hybrid topology, sharding, recompute, MoE,
pipeline. TPU-native substrate: one jax.sharding.Mesh whose named axes are
the communicator groups; collectives are XLA collectives over ICI; parallel
strategies are PartitionSpec annotations compiled by pjit (see mesh.py).
"""
from __future__ import annotations

from .mesh import (  # noqa: F401
    build_mesh, get_mesh, set_mesh, mesh_scope, mesh_axis_size,
    named_sharding, shard_constraint, HYBRID_AXES,
)
from .parallel import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, is_initialized, barrier,
    shard_identity, ParallelEnv,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group,
    all_reduce, all_gather, broadcast, reduce, reduce_scatter, alltoall,
    scatter, send, recv, psum, pmean, ppermute, axis_index,
)
from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401
from .sharding import group_sharded_parallel, shard_optimizer_state  # noqa: F401
from .recompute import recompute, recompute_sequential, recompute_hybrid  # noqa: F401
from .pipeline import (  # noqa: F401
    LayerDesc, SharedLayerDesc, PipelineLayer, PipelineParallel,
    CompiledPipelineParallel, pipeline_scan, pipeline_scan_interleaved,
    pipeline_spmd,
)
from .heter import MeshShardedEmbedding  # noqa: F401
from .dgc import sparse_allreduce, dgc_value_and_grad  # noqa: F401
from .quant_collectives import (  # noqa: F401
    int8_psum, quantize_chunked, dequantize_chunked, sync_grad_groups,
    build_comm_groups, comm_group_stats, default_f32_fallback,
)
from ..ops.ring_attention import (  # noqa: F401
    ring_attention, ulysses_attention, sequence_parallel_attention,
)
from . import fleet  # noqa: F401
from . import mpu  # noqa: F401
from .mpu import split  # noqa: F401

# meta_parallel namespace parity (reference: fleet/meta_parallel/__init__)
from . import mpu as meta_parallel  # noqa: F401

ColumnParallelLinear = mpu.ColumnParallelLinear
RowParallelLinear = mpu.RowParallelLinear
VocabParallelEmbedding = mpu.VocabParallelEmbedding
ParallelCrossEntropy = mpu.ParallelCrossEntropy


class DataParallel:
    """Reference: paddle.DataParallel (fluid/dygraph/parallel.py:399) — wraps
    a layer, syncs params, installs the bucketed EagerReducer (reducer.cc).
    TPU-native: gradients are reduced by XLA when the batch is dp-sharded
    (TrainStep data_axes), so this wrapper only preserves the API shape."""

    def __init__(self, layers, strategy=None, comm_buffer_size_MB=25,
                 last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        self._layers = layers

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)


from .spawn import spawn  # noqa: E402,F401  (reference: distributed/spawn.py:472)
from .store import TCPStore, MasterDaemon  # noqa: E402,F401
from . import launch  # noqa: E402,F401
from . import auto_parallel  # noqa: E402,F401
from .auto_parallel import (  # noqa: E402,F401
    ProcessMesh, shard_tensor, shard_op, reshard,
)
from . import checkpoint  # noqa: E402,F401
from .checkpoint import (save_state_dict, load_state_dict,  # noqa: E402,F401
                         dist_save, dist_load)
from . import ps  # noqa: E402,F401
from . import rpc  # noqa: E402,F401
from . import stream  # noqa: E402,F401
