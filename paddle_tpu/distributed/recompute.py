"""Recompute (activation checkpointing / rematerialisation).

Reference: distributed/fleet/recompute/recompute.py — RecomputeFunction
PyLayer (:69) re-runs forward under backward with saved RNG state;
recompute_sequential (:454); hybrid-aware recompute_hybrid.py.

TPU-native: `jax.checkpoint` (remat) IS recompute — XLA rematerialises the
segment in the backward pass, trading FLOPs for HBM exactly as the reference
does manually, and the threefry key plumbing makes RNG replay automatic
(no CUDA RNG state save/restore needed).
"""
from __future__ import annotations

import jax

from ..core.tensor import Tensor, apply_op


def recompute(function, *args, use_reentrant: bool = True, preserve_rng_state: bool = True,
              params=None, policy=None, **kwargs):
    """Reference: recompute.py:69 — same call shape. Works both eagerly (the
    tape records the remat-wrapped fn: its vjp recomputes) and under jit.

    The segment's parameters are lifted to differentiable inputs of the
    remat region (the analog of RecomputeFunction saving ctx.inputs): the
    layer's params would otherwise be traced as constants and get no grad.
    Auto-detected when `function` is a Layer / bound Layer method; pass
    `params=` explicitly for closures over several layers.
    """
    from ..nn.layer import Layer

    if params is None:
        params = []
        if isinstance(function, Layer):
            params = [p for p in function.parameters() if not p.stop_gradient]
        else:
            self_obj = getattr(function, "__self__", None)
            if isinstance(self_obj, Layer):
                params = [p for p in self_obj.parameters() if not p.stop_gradient]
    n_args = len(args)

    def raw(*arrs):
        from ..jit.api import _swap_params
        arg_arrs, param_arrs = arrs[:n_args], arrs[n_args:]
        # apply_op passes one array per positional arg (non-Tensors were
        # converted); rebuild Tensor slots from their array, keep original
        # Python values for non-Tensor slots (they are trace constants).
        rebuilt = [Tensor(arr, stop_gradient=True) if isinstance(a, Tensor) else a
                   for a, arr in zip(args, arg_arrs)]
        with _swap_params(params, list(param_arrs)):
            out = function(*rebuilt, **kwargs)
        if isinstance(out, (tuple, list)):
            return tuple(o._data if isinstance(o, Tensor) else o for o in out)
        return out._data if isinstance(out, Tensor) else out

    remat_fn = jax.checkpoint(raw, policy=resolve_policy(policy))
    return apply_op("recompute", remat_fn, list(args) + params)


def resolve_policy(policy):
    """Named selective-remat policies (the reference's recompute_granularity
    'full'/'full_attn'/'core_attn' knob, fleet recompute configs — here as
    save-lists over checkpoint_name tags placed in models/gpt.py):

    None        — save nothing: recompute the whole segment (reference
                  default semantics; max memory win, ~2ND extra FLOPs).
    "save_qkv"  — keep the QKV projection output [B,S,3H]; the flash
                  backward reads saved q/k/v instead of recomputing
                  ln1+qkv-proj (≈1/4 of the remat tax for ≈3BSH bytes).
    "save_attn" — also keep the attention context [B,S,H] so the
                  out-projection gradient skips the attention forward.
    "save_big"  — additionally keep the MLP up-projection output [B,S,4H]:
                  backward recomputes only LayerNorms/GELU (elementwise).
    "dots"      — XLA's dots_with_no_batch_dims_saveable policy.
    or any jax.checkpoint_policies callable.
    """
    named = {
        "save_qkv": ("qkv_proj",),
        "save_attn": ("qkv_proj", "attn_ctx"),
        "save_big": ("qkv_proj", "attn_ctx", "mlp_up"),
    }
    if policy in named:
        return jax.checkpoint_policies.save_only_these_names(*named[policy])
    if policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    return policy


def checkpoint_tag(t, name: str):
    """Tag a Tensor's value with jax.ad_checkpoint.checkpoint_name so the
    named policies above can elect to save it; identity outside remat."""
    from jax.ad_checkpoint import checkpoint_name
    return apply_op("ckpt_" + name, lambda a: checkpoint_name(a, name), [t])


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Reference: recompute.py:454 — checkpoint a Sequential in segments."""
    segments = int(ctx.get("segments", 1)) if isinstance(ctx, dict) else 1
    layers = list(functions)
    n = len(layers)
    seg = max(1, n // max(1, segments))

    def run_span(lo, hi):
        def f(*inp):
            y = inp if len(inp) > 1 else inp[0]
            for l in layers[lo:hi]:
                y = l(*y) if isinstance(y, tuple) else l(y)
            return y
        return f

    from ..nn.layer import Layer
    cur = tuple(args)
    i = 0
    while i < n:
        hi = min(n, i + seg)
        span_params = [p for l in layers[i:hi] if isinstance(l, Layer)
                       for p in l.parameters() if not p.stop_gradient]
        out = recompute(run_span(i, hi), *cur, params=span_params, **kwargs)
        cur = out if isinstance(out, tuple) else (out,)
        i = hi
    return cur if len(cur) > 1 else cur[0]


def recompute_hybrid(ctx, function, *args, **kwargs):
    """Reference: recompute_hybrid.py — mp-aware RNG tracker variant; the
    fold_in tracker makes plain recompute already deterministic per-shard."""
    return recompute(function, *args, **kwargs)
