"""PS-style sparse embedding — host-RAM tables with row-sparse optimizers.

Reference (SURVEY §2.2): the brpc parameter server (fluid/distributed/ps/,
31.9k LoC — MemorySparseTable with insert-on-push rows, CTR accessors,
GeoSGD) and HeterPS GPU hashtables (framework/fleet/heter_ps/). SURVEY §7
prescribes the TPU redesign: *don't* port brpc — giant embedding tables live
in host RAM next to the chips, steps pull only the touched rows to device,
and gradients push back row-wise with a sparse optimizer. The dense model
trains on-device as usual; this module supplies the sparse half of the CTR
workflow.

Sharding: ids hash across `num_shards` tables (MemorySparseTable's shard
layout, memory_sparse_table.cc); multi-host deployments place shards on
their owning host (id % world == rank) and batch cross-host pulls through
paddle_tpu.distributed.rpc.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..autograd import PyLayer
from ..nn.layer import Layer


class SparseTable:
    """One shard: growing row store with insert-on-first-touch semantics
    (reference: MemorySparseTable — rows materialize when first pulled,
    ctr_accessor.cc creates feature values lazily)."""

    def __init__(self, dim: int, optimizer: str = "adagrad", lr: float = 0.05,
                 init_scale: float = 0.01,
                 initializer: Optional[Callable] = None, seed: int = 0):
        self.dim = dim
        self.lr = lr
        self.optimizer = optimizer
        self._init_scale = init_scale
        self._initializer = initializer
        self._rng = np.random.RandomState(seed)
        self._slot_of: Dict[int, int] = {}
        cap = 1024
        self._rows = np.zeros((cap, dim), np.float32)
        self._g2 = np.zeros((cap, dim), np.float32) if optimizer == "adagrad" \
            else None
        self._n = 0

    def __len__(self):
        return self._n

    def _grow(self, need: int):
        cap = self._rows.shape[0]
        if self._n + need <= cap:
            return
        new_cap = max(cap * 2, self._n + need)
        self._rows = np.resize(self._rows, (new_cap, self.dim))
        if self._g2 is not None:
            self._g2 = np.resize(self._g2, (new_cap, self.dim))

    def _slots(self, ids: np.ndarray, create: bool) -> np.ndarray:
        out = np.empty(len(ids), np.int64)
        for i, key in enumerate(ids.tolist()):
            slot = self._slot_of.get(key, -1)
            if slot < 0 and create:
                self._grow(1)
                slot = self._n
                self._slot_of[key] = slot
                if self._initializer is not None:
                    self._rows[slot] = self._initializer(self.dim)
                else:
                    self._rows[slot] = self._rng.uniform(
                        -self._init_scale, self._init_scale, self.dim)
                if self._g2 is not None:
                    self._g2[slot] = 0.0
                self._n += 1
            out[i] = slot
        return out

    # -- PS ops --------------------------------------------------------
    def pull(self, ids: np.ndarray) -> np.ndarray:
        """Fetch rows (creating them CTR-style on first touch)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        slots = self._slots(ids, create=True)
        return self._rows[slots].copy()

    def push(self, ids: np.ndarray, grads: np.ndarray):
        """Apply row-sparse update; duplicate ids accumulate
        (reference: sparse table push with gradient merge)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        uniq, inv = np.unique(ids, return_inverse=True)
        g = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(g, inv, grads)
        slots = self._slots(uniq, create=True)
        if self.optimizer == "adagrad":
            self._g2[slots] += g * g
            self._rows[slots] -= self.lr * g / (np.sqrt(self._g2[slots]) + 1e-6)
        else:  # sgd
            self._rows[slots] -= self.lr * g

    # -- persistence (reference: table Save/Load shard files) ----------
    def save(self, path: str):
        keys = np.fromiter(self._slot_of.keys(), np.int64, len(self._slot_of))
        slots = np.fromiter(self._slot_of.values(), np.int64, len(self._slot_of))
        blob = {"keys": keys, "rows": self._rows[slots],
                "dim": self.dim, "optimizer": self.optimizer, "lr": self.lr}
        if self._g2 is not None:
            blob["g2"] = self._g2[slots]
        np.savez(path, **blob)

    def load(self, path: str):
        data = np.load(path if path.endswith(".npz") else path + ".npz")
        keys = data["keys"]
        self.__init__(int(data["dim"]), str(data["optimizer"]),
                      float(data["lr"]), self._init_scale)
        slots = self._slots(keys, create=True)
        self._rows[slots] = data["rows"]
        if self._g2 is not None and "g2" in data:
            self._g2[slots] = data["g2"]


class _Lookup(PyLayer):
    """Tape bridge: forward pulls host rows to device; backward pushes grads
    back to the host table (the pull/push RPC pair of the reference PS,
    ps_client.h:64 PullSparse/PushSparse)."""

    @staticmethod
    def forward(ctx, anchor, embedding, ids_np, out_shape):
        ctx.embedding = embedding
        ctx.ids = ids_np
        rows = embedding._pull(ids_np)
        return Tensor(jnp.asarray(rows.reshape(out_shape)))

    @staticmethod
    def backward(ctx, dy):
        g = np.asarray(dy._data, np.float32).reshape(len(ctx.ids), -1)
        ctx.embedding._push(ctx.ids, g)
        return Tensor(jnp.zeros((), jnp.float32))


class DistributedEmbedding(Layer):
    """Sparse embedding layer over sharded host tables.

    reference: the distributed lookup_table path (fleet PS embedding;
    the_one_ps.py sparse table config). forward(ids[int]) -> [..., dim]."""

    def __init__(self, dim: int, num_shards: int = 1, optimizer: str = "adagrad",
                 lr: float = 0.05, init_scale: float = 0.01, seed: int = 0,
                 name=None):
        super().__init__()
        self.dim = dim
        self.num_shards = num_shards
        self.tables = [SparseTable(dim, optimizer, lr, init_scale, seed=seed + s)
                       for s in range(num_shards)]
        # anchor joins lookups to the autograd tape (host tables are not
        # jax arrays, so the tape needs a differentiable input to traverse)
        self._anchor = self.create_parameter([1])

    # shard router (reference: id % shard_num, memory_sparse_table.cc)
    def _route(self, ids: np.ndarray):
        return (ids % self.num_shards).astype(np.int64)

    def _pull(self, ids: np.ndarray) -> np.ndarray:
        shard = self._route(ids)
        out = np.empty((len(ids), self.dim), np.float32)
        for s in range(self.num_shards):
            m = shard == s
            if m.any():
                out[m] = self.tables[s].pull(ids[m])
        return out

    def _push(self, ids: np.ndarray, grads: np.ndarray):
        shard = self._route(ids)
        for s in range(self.num_shards):
            m = shard == s
            if m.any():
                self.tables[s].push(ids[m], grads[m])

    def forward(self, ids):
        ids_np = np.asarray(ids._data if isinstance(ids, Tensor) else ids,
                            np.int64)
        out_shape = tuple(ids_np.shape) + (self.dim,)
        return _Lookup.apply(self._anchor, self, ids_np.reshape(-1), out_shape)

    def state_size(self) -> int:
        return sum(len(t) for t in self.tables)

    def save(self, prefix: str):
        for s, t in enumerate(self.tables):
            t.save(f"{prefix}.shard{s}")

    def load(self, prefix: str):
        for s, t in enumerate(self.tables):
            t.load(f"{prefix}.shard{s}.npz")
