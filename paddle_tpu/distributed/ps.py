"""PS-style sparse embedding — host-RAM tables with row-sparse optimizers.

Reference (SURVEY §2.2): the brpc parameter server (fluid/distributed/ps/,
31.9k LoC — MemorySparseTable with insert-on-push rows, CTR accessors,
GeoSGD) and HeterPS GPU hashtables (framework/fleet/heter_ps/). SURVEY §7
prescribes the TPU redesign: *don't* port brpc — giant embedding tables live
in host RAM next to the chips, steps pull only the touched rows to device,
and gradients push back row-wise with a sparse optimizer. The dense model
trains on-device as usual; this module supplies the sparse half of the CTR
workflow.

Sharding: ids hash across `num_shards` tables (MemorySparseTable's shard
layout, memory_sparse_table.cc); multi-host deployments place shards on
their owning host (id % world == rank) and batch cross-host pulls through
paddle_tpu.distributed.rpc.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, Optional

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..autograd import PyLayer
from ..nn.layer import Layer


class SparseTable:
    """One shard: growing row store with insert-on-first-touch semantics
    (reference: MemorySparseTable — rows materialize when first pulled,
    ctr_accessor.cc creates feature values lazily)."""

    def __init__(self, dim: int, optimizer: str = "adagrad", lr: float = 0.05,
                 init_scale: float = 0.01,
                 initializer: Optional[Callable] = None, seed: int = 0):
        self.dim = dim
        self.lr = lr
        self.optimizer = optimizer
        self._init_scale = init_scale
        self._initializer = initializer
        self._rng = np.random.RandomState(seed)
        self._slot_of: Dict[int, int] = {}
        cap = 1024
        self._rows = np.zeros((cap, dim), np.float32)
        self._g2 = np.zeros((cap, dim), np.float32) if optimizer == "adagrad" \
            else None
        self._n = 0

    def __len__(self):
        return self._n

    def _grow(self, need: int):
        cap = self._rows.shape[0]
        if self._n + need <= cap:
            return
        new_cap = max(cap * 2, self._n + need)
        self._rows = np.resize(self._rows, (new_cap, self.dim))
        if self._g2 is not None:
            self._g2 = np.resize(self._g2, (new_cap, self.dim))

    def _slots(self, ids: np.ndarray, create: bool) -> np.ndarray:
        out = np.empty(len(ids), np.int64)
        for i, key in enumerate(ids.tolist()):
            slot = self._slot_of.get(key, -1)
            if slot < 0 and create:
                self._grow(1)
                slot = self._n
                self._slot_of[key] = slot
                if self._initializer is not None:
                    self._rows[slot] = self._initializer(self.dim)
                else:
                    self._rows[slot] = self._rng.uniform(
                        -self._init_scale, self._init_scale, self.dim)
                if self._g2 is not None:
                    self._g2[slot] = 0.0
                self._n += 1
            out[i] = slot
        return out

    # -- PS ops --------------------------------------------------------
    def pull(self, ids: np.ndarray) -> np.ndarray:
        """Fetch rows (creating them CTR-style on first touch)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        slots = self._slots(ids, create=True)
        return self._rows[slots].copy()

    def push(self, ids: np.ndarray, grads: np.ndarray):
        """Apply row-sparse update; duplicate ids accumulate
        (reference: sparse table push with gradient merge)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        uniq, inv = np.unique(ids, return_inverse=True)
        g = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(g, inv, grads)
        slots = self._slots(uniq, create=True)
        if self.optimizer == "adagrad":
            self._g2[slots] += g * g
            self._rows[slots] -= self.lr * g / (np.sqrt(self._g2[slots]) + 1e-6)
        else:  # sgd
            self._rows[slots] -= self.lr * g

    def merge_delta(self, ids: np.ndarray, delta: np.ndarray):
        """Additive delta merge (GeoSGD server op: rows += delta;
        reference: memory_sparse_geo_table.cc)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        slots = self._slots(ids, create=True)
        self._rows[slots] += delta.reshape(len(ids), self.dim)

    # -- persistence (reference: table Save/Load shard files) ----------
    def save(self, path: str):
        keys = np.fromiter(self._slot_of.keys(), np.int64, len(self._slot_of))
        slots = np.fromiter(self._slot_of.values(), np.int64, len(self._slot_of))
        blob = {"keys": keys, "rows": self._rows[slots],
                "dim": self.dim, "optimizer": self.optimizer, "lr": self.lr}
        if self._g2 is not None:
            blob["g2"] = self._g2[slots]
        np.savez(path, **blob)

    def load(self, path: str):
        data = np.load(path if path.endswith(".npz") else path + ".npz")
        keys = data["keys"]
        self.__init__(int(data["dim"]), str(data["optimizer"]),
                      float(data["lr"]), self._init_scale)
        slots = self._slots(keys, create=True)
        self._rows[slots] = data["rows"]
        if self._g2 is not None and "g2" in data:
            self._g2[slots] = data["g2"]


class _Lookup(PyLayer):
    """Tape bridge: forward pulls host rows to device; backward pushes grads
    back to the host table (the pull/push RPC pair of the reference PS,
    ps_client.h:64 PullSparse/PushSparse)."""

    @staticmethod
    def forward(ctx, anchor, embedding, ids_np, out_shape):
        ctx.embedding = embedding
        ctx.ids = ids_np
        rows = embedding._pull(ids_np)
        return Tensor(jnp.asarray(rows.reshape(out_shape)))

    @staticmethod
    def backward(ctx, dy):
        g = np.asarray(dy._data, np.float32).reshape(len(ctx.ids), -1)
        ctx.embedding._push(ctx.ids, g)
        return Tensor(jnp.zeros((), jnp.float32))


class DistributedEmbedding(Layer):
    """Sparse embedding layer over sharded host tables.

    reference: the distributed lookup_table path (fleet PS embedding;
    the_one_ps.py sparse table config). forward(ids[int]) -> [..., dim]."""

    def __init__(self, dim: int, num_shards: int = 1, optimizer: str = "adagrad",
                 lr: float = 0.05, init_scale: float = 0.01, seed: int = 0,
                 endpoints=None, table_name: str = "embedding", name=None):
        super().__init__()
        self.dim = dim
        if endpoints:
            # remote mode: each PS endpoint owns one shard (reference: the
            # distributed lookup against brpc PSServers; fleet/ps_runtime)
            from .fleet.ps_runtime import connect_remote_tables
            self.tables = connect_remote_tables(dim, table_name, endpoints,
                                                optimizer, lr,
                                                init_scale=init_scale,
                                                seed=seed)
            self.num_shards = len(self.tables)
        else:
            self.num_shards = num_shards
            self.tables = [SparseTable(dim, optimizer, lr, init_scale,
                                       seed=seed + s)
                           for s in range(num_shards)]
        # anchor joins lookups to the autograd tape (host tables are not
        # jax arrays, so the tape needs a differentiable input to traverse)
        self._anchor = self.create_parameter([1])

    # shard router (reference: id % shard_num, memory_sparse_table.cc)
    def _route(self, ids: np.ndarray):
        return (ids % self.num_shards).astype(np.int64)

    def _pull(self, ids: np.ndarray) -> np.ndarray:
        shard = self._route(ids)
        out = np.empty((len(ids), self.dim), np.float32)
        for s in range(self.num_shards):
            m = shard == s
            if m.any():
                out[m] = self.tables[s].pull(ids[m])
        return out

    def _push(self, ids: np.ndarray, grads: np.ndarray):
        shard = self._route(ids)
        for s in range(self.num_shards):
            m = shard == s
            if m.any():
                self.tables[s].push(ids[m], grads[m])

    def forward(self, ids):
        ids_np = np.asarray(ids._data if isinstance(ids, Tensor) else ids,
                            np.int64)
        out_shape = tuple(ids_np.shape) + (self.dim,)
        return _Lookup.apply(self._anchor, self, ids_np.reshape(-1), out_shape)

    def state_size(self) -> int:
        return sum(len(t) for t in self.tables)

    def save(self, prefix: str):
        for s, t in enumerate(self.tables):
            t.save(f"{prefix}.shard{s}")

    def load(self, prefix: str):
        for s, t in enumerate(self.tables):
            t.load(f"{prefix}.shard{s}.npz")


class GeoSGDEmbedding(DistributedEmbedding):
    """GeoSGD async mode (reference: memory_sparse_geo_table.cc +
    GeoCommunicator in ps/service/communicator/): the trainer updates a
    LOCAL dense copy of the touched rows every step, and only every
    `geo_step` steps exchanges state with the global table — pushing the
    accumulated DELTA (local - pulled base) additively and re-pulling fresh
    rows. Staleness is tolerated by design; that is the GeoSGD contract
    (async CTR training over slow networks).

    Here the "global table" is the sharded host table and the local copy is
    a per-trainer row cache, so single-process semantics match the
    reference's trainer-side GeoCommunicator exactly; multi-trainer
    deployments give each trainer its own GeoSGDEmbedding over a shared
    rpc-backed table.
    """

    def __init__(self, dim: int, geo_step: int = 10, num_shards: int = 1,
                 lr: float = 0.05, init_scale: float = 0.01, seed: int = 0,
                 name=None):
        # global tables hold plain rows; the *local* optimizer is SGD — the
        # geo push is an additive delta merge, not a gradient step
        super().__init__(dim, num_shards, optimizer="sgd", lr=lr,
                         init_scale=init_scale, seed=seed, name=name)
        self.geo_step = int(geo_step)
        self._step = 0
        self._local: Dict[int, np.ndarray] = {}   # id -> local row
        self._base: Dict[int, np.ndarray] = {}    # id -> row at last sync
        self._dirty: set = set()                  # ids touched since sync

    # -- local train-side ----------------------------------------------
    def _pull(self, ids: np.ndarray) -> np.ndarray:
        out = np.empty((len(ids), self.dim), np.float32)
        missing = [i for i, key in enumerate(ids.tolist())
                   if key not in self._local]
        if missing:
            fetched = super()._pull(ids[missing])
            for j, i in enumerate(missing):
                key = int(ids[i])
                self._local[key] = fetched[j].copy()
                self._base[key] = fetched[j].copy()
        for i, key in enumerate(ids.tolist()):
            out[i] = self._local[key]
        return out

    def _push(self, ids: np.ndarray, grads: np.ndarray):
        # local SGD on the cached rows; NO global traffic here
        uniq, inv = np.unique(ids, return_inverse=True)
        missing = np.array([k not in self._local for k in uniq.tolist()])
        if missing.any():  # push without prior pull: materialize rows first
            self._pull(uniq[missing])
        g = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(g, inv, grads.reshape(len(ids), self.dim))
        for i, key in enumerate(uniq.tolist()):
            self._local[key] = self._local[key] - self.lr_value * g[i]
            self._dirty.add(key)
        self._step += 1
        if self._step % self.geo_step == 0:
            self.sync()

    @property
    def lr_value(self):
        return self.tables[0].lr

    # -- geo exchange ---------------------------------------------------
    def sync(self):
        """Push deltas for rows dirtied since the last sync, then re-pull
        fresh state for them (other trainers' merged deltas become visible).
        Un-dirtied cached rows stay stale until touched — the GeoSGD
        staleness contract; syncing only the dirty set keeps the exchange
        proportional to recent work (reference GeoCommunicator sends only
        ids touched in the interval)."""
        if not self._dirty:
            return
        keys = np.fromiter(self._dirty, np.int64, len(self._dirty))
        delta = np.stack([self._local[int(k)] - self._base[int(k)]
                          for k in keys])
        shard = self._route(keys)
        for s in range(self.num_shards):
            m = shard == s
            if m.any():
                self.tables[s].merge_delta(keys[m], delta[m])
        fresh = super()._pull(keys)
        for i, key in enumerate(keys.tolist()):
            self._local[key] = fresh[i].copy()
            self._base[key] = fresh[i].copy()
        self._dirty.clear()

    # -- persistence: reconcile the local cache with the global tables --
    def save(self, prefix: str):
        self.sync()  # unsynced local deltas must not be dropped
        super().save(prefix)

    def load(self, prefix: str):
        super().load(prefix)
        self._local.clear()
        self._base.clear()
        self._dirty.clear()
        self._step = 0


class GraphTable:
    """In-memory graph store with neighbor sampling (reference:
    ps/table/common_graph_table.cc — GNN graph engine: add_graph,
    random_sample_neighbors, node features; and the GPU sampling twin
    framework/fleet/heter_ps/graph_gpu_ps_table.h).

    CSR-compacted on first sample; uniform or weight-proportional sampling
    per node; optional per-node feature rows; random walks for
    deepwalk-style pipelines.
    """

    def __init__(self, seed: int = 0):
        self._src, self._dst, self._w = [], [], []
        self._feat: Dict[int, np.ndarray] = {}
        self._rng = np.random.RandomState(seed)
        self._csr = None

    # -- construction ---------------------------------------------------
    def add_edges(self, src, dst, weight=None):
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        self._src.append(src)
        self._dst.append(dst)
        self._w.append(np.ones(len(src), np.float32) if weight is None
                       else np.asarray(weight, np.float32).reshape(-1))
        self._csr = None

    def set_node_feat(self, ids, feat):
        feat = np.asarray(feat, np.float32)
        for i, key in enumerate(np.asarray(ids, np.int64).reshape(-1).tolist()):
            self._feat[key] = feat[i]

    def get_node_feat(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        dim = len(next(iter(self._feat.values()))) if self._feat else 0
        out = np.zeros((len(ids), dim), np.float32)
        for i, key in enumerate(ids.tolist()):
            if key in self._feat:
                out[i] = self._feat[key]
        return out

    # -- sampling -------------------------------------------------------
    def _build(self):
        if self._csr is not None:
            return
        src = np.concatenate(self._src) if self._src else np.empty(0, np.int64)
        dst = np.concatenate(self._dst) if self._dst else np.empty(0, np.int64)
        w = np.concatenate(self._w) if self._w else np.empty(0, np.float32)
        order = np.argsort(src, kind="stable")
        src, dst, w = src[order], dst[order], w[order]
        uniq, starts = np.unique(src, return_index=True)
        self._csr = {
            "index": {int(u): (int(s), int(e)) for u, s, e in zip(
                uniq, starts, np.append(starts[1:], len(src)))},
            "dst": dst, "w": w}

    def degree(self, ids) -> np.ndarray:
        self._build()
        idx = self._csr["index"]
        return np.array([idx[k][1] - idx[k][0] if k in idx else 0
                         for k in np.asarray(ids, np.int64).reshape(-1).tolist()],
                        np.int64)

    def sample_neighbors(self, ids, sample_size: int,
                         return_weights: bool = False):
        """Per-node neighbor sample (uniform, or weighted when edge weights
        were given). Nodes with no out-edges return empty lists — same
        contract as the reference's actual_sample_size output."""
        self._build()
        idx, dst, w = self._csr["index"], self._csr["dst"], self._csr["w"]
        neigh, weights = [], []
        for key in np.asarray(ids, np.int64).reshape(-1).tolist():
            if key not in idx:
                neigh.append(np.empty(0, np.int64))
                weights.append(np.empty(0, np.float32))
                continue
            s, e = idx[key]
            cand, cw = dst[s:e], w[s:e]
            if e - s <= sample_size:
                take = np.arange(e - s)
            else:
                tot = cw.sum()
                if tot <= 0:  # all-zero weights: fall back to uniform
                    p = None
                else:
                    p = cw / tot
                    if np.allclose(p, p[0]):
                        p = None          # uniform fast path
                    elif np.count_nonzero(p) < sample_size:
                        p = None  # not enough weighted support: uniform
                take = self._rng.choice(e - s, sample_size, replace=False,
                                        p=p)
            neigh.append(cand[take])
            weights.append(cw[take])
        return (neigh, weights) if return_weights else neigh

    def random_walk(self, ids, walk_len: int) -> np.ndarray:
        """Uniform random walks [n, walk_len+1]; walks stop (repeat the
        node) at sinks — deepwalk-style corpus generation."""
        self._build()
        idx, dst = self._csr["index"], self._csr["dst"]
        starts = np.asarray(ids, np.int64).reshape(-1)
        out = np.empty((len(starts), walk_len + 1), np.int64)
        out[:, 0] = starts
        for i, key in enumerate(starts.tolist()):
            cur = key
            for t in range(1, walk_len + 1):
                if cur in idx:
                    s, e = idx[cur]
                    cur = int(dst[s + self._rng.randint(e - s)])
                out[i, t] = cur
        return out


# ---------------------------------------------------------------------------
# CTR accessor + disk-spill tier (VERDICT r1 missing #2: PS production depth)
# ---------------------------------------------------------------------------

class CtrAccessor:
    """Feature lifecycle policy for CTR rows (reference:
    fluid/distributed/ps/table/ctr_accessor.cc — each feature carries
    show/click counters; a pass decays them and Shrink() drops features whose
    score falls below the delete threshold).

    score = nonclk_coeff * (show - click) + click_coeff * click
    """

    def __init__(self, nonclk_coeff: float = 0.1, click_coeff: float = 1.0,
                 show_click_decay_rate: float = 0.98,
                 delete_threshold: float = 0.8):
        self.nonclk_coeff = nonclk_coeff
        self.click_coeff = click_coeff
        self.decay_rate = show_click_decay_rate
        self.delete_threshold = delete_threshold

    def score(self, show: np.ndarray, click: np.ndarray) -> np.ndarray:
        return (self.nonclk_coeff * (show - click)
                + self.click_coeff * click)


class CtrSparseTable(SparseTable):
    """SparseTable whose rows carry show/click counters with decay + shrink
    (reference: memory_sparse_table.cc rows via ctr_accessor).

    push_show_click(ids, shows, clicks) accumulates per-feature counters;
    decay() is the end-of-pass show/click decay; shrink() evicts features
    below the accessor score threshold and returns how many were dropped."""

    def __init__(self, dim: int, accessor: Optional[CtrAccessor] = None,
                 **kw):
        super().__init__(dim, **kw)
        self.accessor = accessor or CtrAccessor()
        self._show = np.zeros(self._rows.shape[0], np.float32)
        self._click = np.zeros(self._rows.shape[0], np.float32)

    def _grow(self, need: int):
        cap = self._rows.shape[0]
        super()._grow(need)
        if self._rows.shape[0] != cap:
            ncap = self._rows.shape[0]
            self._show = np.resize(self._show, ncap)
            self._click = np.resize(self._click, ncap)

    def push_show_click(self, ids, shows, clicks):
        ids = np.asarray(ids, np.int64).reshape(-1)
        slots = self._slots(ids, create=True)
        np.add.at(self._show, slots, np.asarray(shows, np.float32).reshape(-1))
        np.add.at(self._click, slots, np.asarray(clicks, np.float32).reshape(-1))

    def decay(self):
        """End-of-pass counter decay (ctr_accessor.cc UpdateTimeDecay)."""
        self._show[:self._n] *= self.accessor.decay_rate
        self._click[:self._n] *= self.accessor.decay_rate

    def shrink(self) -> int:
        """Drop features scoring below delete_threshold (table Shrink)."""
        keys = list(self._slot_of.items())
        dropped = 0
        keep_keys = []
        for key, slot in keys:
            sc = self.accessor.score(self._show[slot], self._click[slot])
            if sc < self.accessor.delete_threshold:
                dropped += 1
            else:
                keep_keys.append((key, slot))
        if dropped:
            # compact the surviving rows
            rows = self._rows[[s for _, s in keep_keys]].copy()
            g2 = self._g2[[s for _, s in keep_keys]].copy() \
                if self._g2 is not None else None
            show = self._show[[s for _, s in keep_keys]].copy()
            click = self._click[[s for _, s in keep_keys]].copy()
            self._slot_of = {k: i for i, (k, _) in enumerate(keep_keys)}
            self._n = len(keep_keys)
            self._rows[:self._n] = rows
            if g2 is not None:
                self._g2[:self._n] = g2
            self._show[:self._n] = show
            self._click[:self._n] = click
        return dropped

    def save(self, path: str):
        keys = np.fromiter(self._slot_of.keys(), np.int64, len(self._slot_of))
        slots = np.fromiter(self._slot_of.values(), np.int64, len(self._slot_of))
        blob = {"keys": keys, "rows": self._rows[slots],
                "dim": self.dim, "optimizer": self.optimizer, "lr": self.lr,
                "show": self._show[slots], "click": self._click[slots]}
        if self._g2 is not None:
            blob["g2"] = self._g2[slots]
        np.savez(path, **blob)

    def load(self, path: str):
        data = np.load(path if path.endswith(".npz") else path + ".npz")
        acc = self.accessor
        self.__init__(int(data["dim"]), accessor=acc,
                      optimizer=str(data["optimizer"]), lr=float(data["lr"]))
        slots = self._slots(data["keys"], create=True)
        self._rows[slots] = data["rows"]
        if self._g2 is not None and "g2" in data:
            self._g2[slots] = data["g2"]
        if "show" in data:
            self._show[slots] = data["show"]
            self._click[slots] = data["click"]


class DiskSpillSparseTable(SparseTable):
    """RAM-bounded shard with a disk tier (reference: ssd_sparse_table.cc
    over rocksdb — hot rows in memory, the long tail on disk).

    Rows beyond `max_ram_rows` spill least-recently-touched to an on-disk
    memmap heap (row + accumulator), and spill files persist across
    save/load, so tables larger than RAM keep exact trajectories."""

    def __init__(self, dim: int, max_ram_rows: int = 1 << 16,
                 spill_dir: Optional[str] = None, **kw):
        super().__init__(dim, **kw)
        import tempfile
        self.max_ram_rows = int(max_ram_rows)
        self.spill_dir = spill_dir or tempfile.mkdtemp(prefix="ptpu_ps_spill_")
        os.makedirs(self.spill_dir, exist_ok=True)
        self._disk_path = os.path.join(self.spill_dir, "heap.dat")
        self._disk_index: Dict[int, int] = {}   # id -> disk slot
        self._disk_free: list = []
        self._disk_cap = 0
        self._disk = None
        self._lru: Dict[int, None] = {}          # insertion-ordered touches
        self._free_slots = []
        self._protect = frozenset()   # current batch: must not spill (their
                                      # RAM slots are live in the caller)

    # -- disk heap ------------------------------------------------------
    def _disk_width(self):
        return self.dim * (2 if self._g2 is not None else 1)

    def _ensure_disk(self, need_slots: int):
        need = len(self._disk_index) + need_slots
        if self._disk is not None and need <= self._disk_cap:
            return
        new_cap = max(1024, self._disk_cap * 2, need)
        new = np.memmap(self._disk_path + ".new", np.float32, mode="w+",
                        shape=(new_cap, self._disk_width()))
        if self._disk is not None:
            new[:self._disk_cap] = self._disk[:]
            del self._disk
        new.flush()
        os.replace(self._disk_path + ".new", self._disk_path)
        self._disk = np.memmap(self._disk_path, np.float32, mode="r+",
                               shape=(new_cap, self._disk_width()))
        self._disk_cap = new_cap

    def _spill(self, n: int):
        """Move the n least-recently-touched RAM rows to disk (never the
        current batch's rows — their slots are live in the caller)."""
        victims = []
        for k in list(self._lru.keys()):
            if len(victims) >= n:
                break
            if k not in self._protect:
                victims.append(k)
        if not victims:
            return
        self._ensure_disk(len(victims))
        for k in victims:
            slot = self._slot_of.pop(k)
            dslot = self._disk_free.pop() if self._disk_free \
                else len(self._disk_index)
            rec = self._rows[slot] if self._g2 is None else np.concatenate(
                [self._rows[slot], self._g2[slot]])
            self._disk[dslot, :len(rec)] = rec
            self._disk_index[k] = dslot
            self._lru.pop(k, None)
            self._free_ram_slot(slot)

    def _free_ram_slot(self, slot):
        self._free_slots.append(slot)

    def _slots(self, ids: np.ndarray, create: bool) -> np.ndarray:
        out = np.empty(len(ids), np.int64)
        for i, key in enumerate(ids.tolist()):
            slot = self._slot_of.get(key, -1)
            if slot < 0 and key in self._disk_index:
                # restore from disk (row + accumulator round-trip)
                slot = self._alloc_ram_slot()
                rec = np.array(self._disk[self._disk_index[key]])
                self._rows[slot] = rec[:self.dim]
                if self._g2 is not None:
                    self._g2[slot] = rec[self.dim:2 * self.dim]
                self._disk_free.append(self._disk_index.pop(key))
                self._slot_of[key] = slot
            elif slot < 0 and create:
                slot = self._alloc_ram_slot()
                self._slot_of[key] = slot
                if self._initializer is not None:
                    self._rows[slot] = self._initializer(self.dim)
                else:
                    self._rows[slot] = self._rng.uniform(
                        -self._init_scale, self._init_scale, self.dim)
                if self._g2 is not None:
                    self._g2[slot] = 0.0
            out[i] = slot
            if slot >= 0:
                self._lru.pop(key, None)
                self._lru[key] = None
        return out

    def _alloc_ram_slot(self) -> int:
        if self._free_slots:
            return self._free_slots.pop()
        if len(self._slot_of) >= self.max_ram_rows:
            self._spill(max(1, self.max_ram_rows // 8))
            if self._free_slots:
                return self._free_slots.pop()
        # soft overflow: a batch larger than the RAM budget grows past the
        # cap; _enforce_cap() spills back down after the batch completes
        self._grow(1)
        slot = self._n
        self._n += 1
        return slot

    def _enforce_cap(self):
        excess = len(self._slot_of) - self.max_ram_rows
        if excess > 0:
            self._spill(excess)

    def pull(self, ids: np.ndarray) -> np.ndarray:
        flat = np.asarray(ids, np.int64).reshape(-1)
        self._protect = frozenset(flat.tolist())
        try:
            return super().pull(flat)
        finally:
            self._protect = frozenset()
            self._enforce_cap()

    def push(self, ids: np.ndarray, grads: np.ndarray):
        flat = np.asarray(ids, np.int64).reshape(-1)
        self._protect = frozenset(flat.tolist())
        try:
            return super().push(flat, grads)
        finally:
            self._protect = frozenset()
            self._enforce_cap()

    def __len__(self):
        return len(self._slot_of) + len(self._disk_index)

    def save(self, path: str):
        """Persist BOTH tiers (the SSD table's Save walks rocksdb too)."""
        ids, rows, g2s = [], [], []
        for k, slot in self._slot_of.items():
            ids.append(k)
            rows.append(self._rows[slot].copy())
            if self._g2 is not None:
                g2s.append(self._g2[slot].copy())
        for k, dslot in self._disk_index.items():
            rec = np.array(self._disk[dslot])
            ids.append(k)
            rows.append(rec[:self.dim])
            if self._g2 is not None:
                g2s.append(rec[self.dim:2 * self.dim])
        blob = {"keys": np.asarray(ids, np.int64),
                "rows": np.stack(rows) if rows
                else np.zeros((0, self.dim), np.float32),
                "dim": self.dim, "optimizer": self.optimizer, "lr": self.lr,
                "max_ram_rows": self.max_ram_rows}
        if self._g2 is not None:
            blob["g2"] = (np.stack(g2s) if g2s
                          else np.zeros((0, self.dim), np.float32))
        np.savez(path, **blob)

    def load(self, path: str):
        data = np.load(path if path.endswith(".npz") else path + ".npz")
        self.__init__(int(data["dim"]), max_ram_rows=int(data["max_ram_rows"]),
                      spill_dir=self.spill_dir,
                      optimizer=str(data["optimizer"]), lr=float(data["lr"]))
        keys = data["keys"]
        self._protect = frozenset(np.asarray(keys).tolist())
        try:
            slots = self._slots(keys, create=True)
            self._rows[slots] = data["rows"]
            if self._g2 is not None and "g2" in data:
                self._g2[slots] = data["g2"]
        finally:
            self._protect = frozenset()
            self._enforce_cap()
