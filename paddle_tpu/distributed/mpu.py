"""Tensor (model) parallel layers — "mpu".

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding (:35), ColumnParallelLinear (:173), RowParallelLinear
(:332), ParallelCrossEntropy (:498) — which hold the LOCAL weight shard and
call explicit collectives (_c_identity/_c_concat/_mp_allreduce, mp_ops.py:27-219).

TPU-native inversion: layers hold the FULL logical weight annotated with a
PartitionSpec over the `mp` mesh axis. Under jit (paddle_tpu.jit.TrainStep)
pjit shards the weight and XLA inserts exactly the collectives the reference
hand-writes — identity forward + allreduce backward for column, allreduce
forward for row — as sharding propagation. Eagerly (no mesh) the same layer
is an ordinary dense layer, so single-chip tests are the correctness
reference. `shard_constraint` pins activation layouts where propagation
would otherwise pick a worse one.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from jax import shard_map

from ..core.tensor import Tensor, Parameter, apply_op
from ..nn.layer import Layer
from ..nn import initializer as I
from . import mesh as _mesh


class VocabParallelEmbedding(Layer):
    """Reference: mp_layers.py:35 — embedding table sharded over vocab.

    Weight pspec P("mp", None): each mp shard owns a contiguous vocab range.
    XLA lowers the (sharded-operand) gather to the same masked-lookup+psum
    the reference writes manually (c_embedding op, operators/collective/
    c_embedding_op.cu).
    """

    def __init__(self, num_embeddings: int, embedding_dim: int, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.pspec = P("mp", None)

    def forward(self, x):
        q8 = _q8_payload(self.weight)

        def fn(ids, w):
            if q8 is not None:
                # int8 row gather + per-row scale: the full-width table is
                # never reconstructed for an O(B) lookup
                qv, sv = q8
                out = (jnp.take(qv, ids, axis=0).astype(jnp.float32)
                       * jnp.take(sv, ids, axis=0)).astype(w.dtype)
            else:
                out = jnp.take(w, ids, axis=0)
            return _act_constraint(out)
        return apply_op("vocab_parallel_embedding", fn, [x, self.weight])


def _act_constraint(a, last=None):
    """Pin a batch-leading activation's layout WITHOUT undoing data
    parallelism: dim 0 stays on `dp`, dim 1 (when rank >= 3) on `sp`,
    the last dim as requested (`"mp"` for a tensor-sharded feature dim,
    None for replicated). The original mpu constraints pinned every
    non-feature dim replicated — under a dp mesh the partitioner then
    all-gathered the batch dim back together at EVERY layer boundary
    (the accidental resharding the ISSUE-15 sharding lint exists to
    catch; found by its collective inventory on the dp train step).
    Absent axes are dropped by mesh.filter_spec, so the same constraint
    degrades gracefully on any mesh."""
    entries = ["dp"] + [None] * (a.ndim - 1)
    if a.ndim >= 3:
        entries[1] = "sp"
    if a.ndim >= 2:
        entries[-1] = last
    return _mesh.shard_constraint(a, *entries)


def _q8_payload(weight_tensor):
    """Weight-only int8 decode payload (set by GPT's generate_static while
    tracing with weight_dtype="int8"): (int8 codes, per-channel scale).
    When present, matmul consumers stream the int8 bytes through the
    Pallas dequant-in-register kernel instead of reading a full-width
    dequantized copy (ops/pallas/int8_matmul.py)."""
    return getattr(weight_tensor, "_q8", None)


class ColumnParallelLinear(Layer):
    """Reference: mp_layers.py:173 — weight columns sharded over mp; forward
    is identity-in/allreduce-grad; output stays sharded unless gather_output."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, gather_output: bool = True,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.pspec = P(None, "mp")
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.pspec = P("mp")

    def forward(self, x, shard_output: bool = True):
        # shard_output=False skips the mp constraint on the output: the
        # caller will apply its own sharding after a reshape that the
        # contiguous [*, out] mp-tiling cannot survive (e.g. the fused
        # qkv [B,S,3H] -> [B,S,3,nh,hd] split in paged serving, where a
        # head-axis constraint AFTER the reshape is a free local slice
        # but an mp constraint BEFORE it forces a partitioner collective).
        gather = self.gather_output
        q8 = _q8_payload(self.weight)

        def fn(x_, w, *b):
            if q8 is not None:
                from ..ops.pallas.int8_matmul import int8_linear_nd
                y = int8_linear_nd(x_, q8[0], q8[1].reshape(-1),
                                   b[0] if b else None)
            else:
                y = jnp.matmul(x_, w)
                if b:
                    y = y + b[0]
            if not gather and shard_output:
                y = _act_constraint(y, "mp")
            return y

        args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
        return apply_op("column_parallel_linear", fn, args)


class RowParallelLinear(Layer):
    """Reference: mp_layers.py:332 — weight rows sharded over mp; input is
    expected sharded on its last dim; XLA inserts the forward allreduce
    (the reference's mp_allreduce_sum) from the contracting-dim sharding."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, input_is_parallel: bool = False,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.pspec = P("mp", None)
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.pspec = P()

    def forward(self, x):
        q8 = _q8_payload(self.weight)

        def fn(x_, w, *b):
            x_ = _act_constraint(x_, "mp")
            if q8 is not None:
                from ..ops.pallas.int8_matmul import int8_linear_nd
                y = int8_linear_nd(x_, q8[0], q8[1].reshape(-1))
            else:
                # Pin the weight's contracting dim too: with BOTH operands
                # sharded on the contraction the partitioner must lower
                # partial-dot + all-reduce. Without it, on small shapes
                # (b=1 prefill) the cost model prefers all-gathering the
                # activation and doing a local full matmul — legal, but it
                # breaks the all-reduce-only serving CommPlan. In training
                # the weight already lives at P("mp", None), so this is a
                # no-op; in serving (weights replicated) it is a free
                # local slice.
                w = _mesh.shard_constraint(w, "mp", None)
                y = jnp.matmul(x_, w)
            y = _act_constraint(y)
            if b:
                y = y + b[0]
            return y

        args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
        return apply_op("row_parallel_linear", fn, args)


class ParallelCrossEntropy(Layer):
    """Reference: mp_layers.py:498 → c_softmax_with_cross_entropy op: CE over
    vocab-sharded logits without materialising the full softmax on one rank.
    TPU-native: computed on the global view with a sharding constraint keeping
    logits sharded over mp through the log-sum-exp (XLA keeps the reduction
    distributed); numerically fp32.
    """

    def __init__(self, mp_group=None, name=None, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        ii = self.ignore_index

        def fn(lg, lb):
            lg32 = lg.astype(jnp.float32)
            lg32 = _act_constraint(lg32, "mp")
            lse = jax.nn.logsumexp(lg32, axis=-1, keepdims=True)
            lb_ = lb[..., None] if lb.ndim == lg.ndim - 1 else lb
            picked = jnp.take_along_axis(lg32, jnp.maximum(lb_, 0).astype(jnp.int32), axis=-1)
            loss = lse - picked
            loss = jnp.where(lb_ == ii, 0.0, loss)
            return loss

        return apply_op("parallel_cross_entropy", fn, [logits, labels])


# ---------------------------------------------------------------------------
# mp_ops analogs (reference: fleet/layers/mpu/mp_ops.py) — explicit-layout
# helpers for code written against the sharded view.
# ---------------------------------------------------------------------------

def _c_identity(x, group=None):
    """Forward identity / backward allreduce over mp — under pjit this is
    exactly what sharding propagation emits for a replicated-in, sharded-out
    matmul; provided for API parity (mp_ops.py:27)."""
    return x


def _c_split(x, group=None):
    """Split last dim over mp ranks (mp_ops.py:158): a sharding constraint."""
    if isinstance(x, Tensor):
        return apply_op("c_split", lambda a: _mesh.shard_constraint(
            a, *([None] * (a.ndim - 1)), "mp"), [x])
    return _mesh.shard_constraint(x, *([None] * (x.ndim - 1)), "mp")


def _c_concat(x, group=None):
    """Concat shards to replicated (mp_ops.py:87)."""
    if isinstance(x, Tensor):
        return apply_op("c_concat", lambda a: _mesh.shard_constraint(
            a, *([None] * a.ndim)), [x])
    return _mesh.shard_constraint(x, *([None] * x.ndim))


def _mp_allreduce(x, group=None):
    return _c_concat(x, group)


def split(x, size, operation: str = "linear", axis: int = 0, num_partitions=None,
          gather_out: bool = True, weight_attr=None, bias_attr=None, name=None):
    """Reference: paddle.distributed.split (mp_ops.py:653) — builds a TP
    layer for you. Returns the layer output for API parity."""
    if operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1], weight_attr, bias_attr is not False)
        else:
            layer = ColumnParallelLinear(size[0], size[1], weight_attr,
                                         bias_attr is not False, gather_output=gather_out)
        return layer(x)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1], weight_attr)
        return layer(x)
    raise ValueError(f"unsupported operation {operation!r}")


class _RNGStatesTracker:
    """Reference: fleet/layers/mpu/random.py RNGStatesTracker — distinct
    dropout streams inside vs outside TP regions. TPU-native: fold_in on the
    global threefry key with a per-name constant; determinism is structural
    (SURVEY §7 determinism note)."""

    def __init__(self):
        self._names = {}

    def add(self, name, seed):
        self._names[name] = seed

    def rng_state(self, name="model_parallel_rng"):
        import contextlib
        import zlib
        from ..core import random as _random

        @contextlib.contextmanager
        def scope():
            # stable seed (crc32, not PYTHONHASHSEED-randomized hash: multi-
            # host SPMD needs every process to fold the same constant), and
            # a fresh base via split_key() so successive eager entries draw
            # distinct streams (the reference tracker advances its state too)
            seed = self._names.get(name, zlib.crc32(name.encode()) & 0x7FFFFFFF)
            key = jax.random.fold_in(_random.split_key(), seed)
            with _random.trace_key_scope(key):
                yield
        return scope()


_tracker = _RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker
