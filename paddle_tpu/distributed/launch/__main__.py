"""`python -m paddle_tpu.distributed.launch` — the cluster launch CLI.

Reference: python/paddle/distributed/launch/main.py (SURVEY §2.2 Launch CLI):
elastic multi-node process manager with HTTPMaster/ETCDMaster rendezvous.
Usage mirrors the reference:

    python -m paddle_tpu.distributed.launch \
        --nnodes 2 --master 10.0.0.1:6070 --nproc_per_node 1 train.py --args

On TPU pods, run one process per host (the default nproc_per_node=1); each
process claims all local chips and jax.distributed stitches the pod into one
world. `--devices_per_proc N` runs CPU-emulated hosts for testing (virtual
XLA devices), the analog of the reference's 2-GPU CI harness
(test_parallel_dygraph_dataparallel.py:157).
"""
from __future__ import annotations

import argparse
import sys

from .controllers import CollectiveController, PSController, RpcController


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a distributed SPMD training job")
    p.add_argument("--nnodes", default="1",
                   help="node count, or elastic range 'min:max'")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (1 per TPU host)")
    p.add_argument("--master", default=None,
                   help="rendezvous store address host:port (rank-0 node)")
    p.add_argument("--rank", type=int, default=-1,
                   help="this node's rank; -1 = assigned by the master")
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--elastic_level", type=int, default=0,
                   help="0=off, 1=fault-tolerant restart (reference "
                        "FAULT_TOLERANCE), 2=elastic scale (ELASTIC)")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--elastic_ttl", type=float, default=60.0,
                   help="heartbeat TTL (s) for elastic membership "
                        "(reference: etcd TTL, elastic/manager.py)")
    p.add_argument("--hold_patience", type=float, default=None,
                   help="seconds to wait below quorum before exiting "
                        "(default 3*elastic_ttl)")
    p.add_argument("--start_port", type=int, default=6170)
    p.add_argument("--coordinator_port", type=int, default=6171)
    p.add_argument("--devices_per_proc", type=int, default=0,
                   help="emulate N CPU devices per process (testing)")
    p.add_argument("--run_mode", default="collective",
                   choices=["collective", "ps", "rpc"],
                   help="collective (SPMD over chips), ps (parameter "
                        "servers + trainers), or rpc (paddle.distributed."
                        "rpc process group; reference rpc controller)")
    p.add_argument("--server_num", type=int, default=1,
                   help="[ps mode] PS shard processes")
    p.add_argument("--trainer_num", type=int, default=1,
                   help="[ps mode] trainer processes")
    p.add_argument("--poll_interval", type=float, default=0.5)
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch(argv=None) -> int:
    args = parse_args(argv)
    ctl = {"ps": PSController, "rpc": RpcController}.get(
        args.run_mode, CollectiveController)
    return ctl(args).run()


if __name__ == "__main__":
    sys.exit(launch())
