"""Launch controllers — process orchestration for SPMD jobs.

Reference: python/paddle/distributed/launch/controllers/{collective,master,
watcher}.py — CollectiveController builds the rank env for each trainer,
HTTPMaster/ETCDMaster assign node ranks, the watcher restarts on failure per
elastic level (fleet/elastic/manager.py:41 FAULT_TOLERANCE vs ELASTIC).

TPU-native deltas: the per-process env contract is jax.distributed's
(coordinator address + process id + process count) rather than
PADDLE_TRAINER_ENDPOINTS socket lists (both are set, for compat); rendezvous
is our TCPStore (store.py) standing in for HTTPMaster/etcd.
"""
from __future__ import annotations

import os
import sys
import time
import uuid
from typing import List, Optional

from ..store import TCPStore, MasterDaemon
from ..fleet.elastic import ElasticManager, ElasticStatus
from .job import Container, Pod

# _watch sentinels (reference: ElasticStatus driving the manager loop,
# elastic/manager.py:46)
MEMBERSHIP_RESTART = -1001   # rank-table rebuild + trainer restart
QUORUM_EXIT = -1002          # below np_min past patience: terminal exit


class CollectiveController:
    """One instance runs per node; rank-0's also hosts the master store."""

    def __init__(self, args):
        self.args = args
        self.pod = Pod()
        self.store: Optional[TCPStore] = None
        self.job_id = args.job_id or "default"
        self.node_rank = 0
        self.nnodes = 1
        self.restarts = 0

        nn = str(args.nnodes)
        if ":" in nn:   # elastic range min:max
            self.nnodes_min, self.nnodes_max = map(int, nn.split(":"))
            self.elastic = True
        else:
            self.nnodes_min = self.nnodes_max = int(nn)
            self.elastic = False
        self.nnodes = self.nnodes_min
        self._manager: Optional[ElasticManager] = None
        self._hold_since: Optional[float] = None

    # ------------------------------------------------------------- rendezvous
    def _rendezvous(self):
        """Sign in at the master store and obtain this node's rank."""
        master = self.args.master
        if self.nnodes_max <= 1 and not master:
            return  # single node: no store needed
        if master:
            host, port = master.rsplit(":", 1)
            self.store = self._connect_or_host(host, int(port))
        else:
            self.store = TCPStore(is_master=True, world_size=self.nnodes)
        if self.args.rank >= 0:
            self.node_rank = self.args.rank
            self.store.set(f"{self.job_id}/node/{self.node_rank}", _hostname())
        else:
            self.node_rank = self.store.add(f"{self.job_id}/nodes", 1) - 1
            self.store.set(f"{self.job_id}/node/{self.node_rank}", _hostname())
        # wait for quorum
        self.store.barrier(f"signin_{self.restarts}", self.nnodes)

    def _connect_or_host(self, host: str, port: int) -> TCPStore:
        """Join the master store, hosting it if nobody has yet.

        --rank 0 always hosts. With auto-assigned ranks (-1), every node
        first tries to connect; the one that finds no server binds it — a
        bind race loser just falls back to connecting (reference:
        controllers/master.py HTTPMaster 'start on rank0 else poll')."""
        if self.args.rank == 0:
            return TCPStore(host, port, is_master=True, world_size=self.nnodes)
        try:
            return TCPStore(host, port, world_size=self.nnodes, timeout=5)
        except TimeoutError:
            pass
        try:
            return TCPStore(host, port, is_master=True, world_size=self.nnodes)
        except OSError:  # lost the bind race: a peer is hosting now
            return TCPStore(host, port, world_size=self.nnodes)

    # ------------------------------------------------------------- build pod
    def build_pod(self):
        args = self.args
        nproc = args.nproc_per_node
        world = self.nnodes * nproc
        coordinator = self._coordinator_addr()
        base_port = args.start_port
        endpoints = [f"127.0.0.1:{base_port + i}" for i in range(world)]

        self.pod.clear()
        for local_rank in range(nproc):
            global_rank = self.node_rank * nproc + local_rank
            env = {
                # TPU-native contract (consumed by init_parallel_env)
                "PADDLE_TPU_COORDINATOR": coordinator,
                "PADDLE_TPU_NUM_PROCESSES": str(world),
                "PADDLE_TPU_PROCESS_ID": str(global_rank),
                "PADDLE_TPU_LOCAL_RANK": str(local_rank),
                # reference compat env (test_dist_base.py:899 contract)
                "PADDLE_TRAINER_ID": str(global_rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_LOCAL_RANK": str(local_rank),
                "PADDLE_CURRENT_ENDPOINT": endpoints[global_rank] if global_rank < len(endpoints) else "",
                "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
                "FLAGS_selected_devices": str(local_rank),
            }
            if args.devices_per_proc:
                env["JAX_PLATFORMS"] = "cpu"
                env["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                    f" --xla_force_host_platform_device_count={args.devices_per_proc}")
            log = os.path.join(args.log_dir,
                               f"workerlog.{global_rank}") if args.log_dir else None
            cmd = [sys.executable, "-u", args.script] + list(args.script_args)
            self.pod.add(Container(cmd, env, log))

    def _coordinator_addr(self) -> str:
        if self.args.master and self.nnodes > 1:
            host, _ = self.args.master.rsplit(":", 1)
            return f"{host}:{self.args.coordinator_port}"
        return f"127.0.0.1:{self.args.coordinator_port}"

    # ------------------------------------------------------------- elastic
    def _start_elastic(self):
        """Join the heartbeat ring; derive nnodes/rank from LIVE membership
        (a late joiner sees the running nodes and slots in after them)."""
        if not self.elastic or self.store is None:
            return
        ttl = getattr(self.args, "elastic_ttl", 60.0)
        self._manager = ElasticManager(
            self.store, self.job_id, node_id=f"{self.node_rank:06d}",
            np_min=self.nnodes_min, np_max=self.nnodes_max,
            ttl=ttl, beat_interval=max(0.2, ttl / 6.0))
        self._manager.start()
        self._apply_membership()

    def _apply_membership(self):
        """Rank-table rebuild (reference: manager.py:126 — rank re-assign +
        endpoint re-render on membership change)."""
        live = self._manager.live_nodes()
        self.nnodes = max(1, min(len(live), self.nnodes_max))
        me = self._manager.node_id
        self.node_rank = live.index(me) if me in live else 0
        self._manager.mark_epoch()

    # ------------------------------------------------------------- run loop
    def run(self) -> int:
        self._rendezvous()
        self._start_elastic()
        while True:
            self.build_pod()
            self.pod.start()
            code = self._watch()
            if code == 0:
                if self._manager:
                    self._manager.stop()
                return 0
            if code == QUORUM_EXIT:
                # terminal: membership stayed below np_min past patience
                self.pod.terminate()
                if self._manager:
                    self._manager.stop()
                return 9
            if code == MEMBERSHIP_RESTART:
                # node joined/left: rebuild the rank table, re-render the
                # env, restart trainers (reference ElasticStatus.RESTART)
                self.pod.terminate()
                old = (self.nnodes, self.node_rank)
                self._apply_membership()
                self._hold_since = None
                sys.stderr.write(
                    f"[launch] membership changed: nnodes {old[0]} -> "
                    f"{self.nnodes}, rank {old[1]} -> {self.node_rank}; "
                    f"restarting trainers\n")
                continue
            # failure: restart per elastic level (reference ElasticStatus
            # RESTART path, fleet/elastic/manager.py:46)
            if self.args.elastic_level <= 0 or \
                    self.restarts >= self.args.max_restarts:
                self.pod.terminate()
                if self._manager:
                    self._manager.stop()
                return code
            self.restarts += 1
            sys.stderr.write(
                f"[launch] worker failed (exit {code}); restart "
                f"{self.restarts}/{self.args.max_restarts}\n")
            self.pod.terminate()
            if self.store:
                self.store.barrier(f"restart_{self.restarts}", self.nnodes)

    def _watch(self) -> int:
        while True:
            if self.pod.done():
                return self.pod.exit_code()
            failed = self.pod.failed()
            if failed is not None:
                tail = failed.tail_log()
                if tail:
                    sys.stderr.write(f"[launch] failed worker log tail:\n{tail}\n")
                self.pod.terminate()
                return failed.exit_code or 1
            if self._manager is not None:
                st = self._manager.watch()
                if st == ElasticStatus.RESTART:
                    return MEMBERSHIP_RESTART
                if st == ElasticStatus.HOLD and \
                        len(self._manager.live_nodes()) < self.nnodes_min:
                    # below quorum: wait for rejoin, escalate after patience
                    now = time.time()
                    patience = getattr(self.args, "hold_patience", None) \
                        or 3 * self._manager.ttl
                    if self._hold_since is None:
                        self._hold_since = now
                    elif now - self._hold_since > patience:
                        sys.stderr.write(
                            "[launch] below elastic quorum past patience; "
                            "exiting\n")
                        return QUORUM_EXIT
                else:
                    self._hold_since = None
            time.sleep(self.args.poll_interval)


def _hostname() -> str:
    import socket
    return socket.gethostname()


def _node_ip() -> str:
    import socket
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return socket.gethostbyname(socket.gethostname())


class RpcController(CollectiveController):
    """RPC-mode job controller (reference: launch/controllers/rpc.py
    RpcController — wires a process group for paddle.distributed.rpc
    instead of collectives: every worker gets the rpc master endpoint
    (peer 0), its own worker endpoint, and its global rank; the job is
    done when all workers exit)."""

    def build_pod(self):
        args = self.args
        nproc = args.nproc_per_node
        world = self.nnodes * nproc
        base_port = args.start_port
        master_host = (args.master.rsplit(":", 1)[0]
                       if args.master and self.nnodes > 1 else "127.0.0.1")
        master_ep = f"{master_host}:{base_port}"
        # endpoint hints: single-node jobs use loopback; multi-node workers
        # advertise this node's address so peers can reach them (init_rpc
        # registers its ACTUAL ip:port in the store either way — these fix
        # the port so firewalled clusters can pre-open it)
        my_host = _node_ip() if self.nnodes > 1 else "127.0.0.1"
        endpoints = [f"{my_host}:{base_port + 1 + i}" for i in range(world)]

        self.pod.clear()
        for local_rank in range(nproc):
            global_rank = self.node_rank * nproc + local_rank
            env = {
                "PADDLE_MASTER": master_ep,
                "PADDLE_WORKER_ENDPOINT": endpoints[global_rank],
                "PADDLE_TRAINER_ID": str(global_rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_LOCAL_RANK": str(local_rank),
            }
            if args.devices_per_proc:
                env["JAX_PLATFORMS"] = "cpu"
            log = os.path.join(
                args.log_dir,
                f"workerlog.{global_rank}") if args.log_dir else None
            cmd = [sys.executable, "-u", args.script] + list(args.script_args)
            self.pod.add(Container(cmd, env, log))


class PSController(CollectiveController):
    """Parameter-server job controller (reference: launch/controllers/ps.py
    — spawns PSERVER and TRAINER processes with the PaddleCloud role env).
    Single-node form: --run_mode ps --server_num N --trainer_num M."""

    def build_pod(self):
        args = self.args
        n_servers = getattr(args, "server_num", 1)
        n_trainers = getattr(args, "trainer_num", 1)
        base_port = args.start_port
        server_eps = [f"127.0.0.1:{base_port + i}" for i in range(n_servers)]
        barrier_ep = f"127.0.0.1:{base_port + n_servers}"

        self.pod.clear()
        common = {
            "PADDLE_PSERVER_ENDPOINTS": ",".join(server_eps),
            "PADDLE_TRAINERS_NUM": str(n_trainers),
            "PADDLE_TRAINERS_BARRIER_STORE": barrier_ep,
        }
        if args.devices_per_proc:
            common["JAX_PLATFORMS"] = "cpu"
        for i, ep in enumerate(server_eps):
            env = dict(common)
            env.update({"TRAINING_ROLE": "PSERVER",
                        "PADDLE_PORT": ep.rsplit(":", 1)[1],
                        "POD_IP": "127.0.0.1",
                        "PADDLE_PSERVER_ID": str(i),
                        "JAX_PLATFORMS": "cpu"})  # servers never touch chips
            log = os.path.join(args.log_dir,
                               f"serverlog.{i}") if args.log_dir else None
            self.pod.add(Container(
                [sys.executable, "-u", args.script] + list(args.script_args),
                env, log))
        for i in range(n_trainers):
            env = dict(common)
            env.update({"TRAINING_ROLE": "TRAINER",
                        "PADDLE_TRAINER_ID": str(i)})
            log = os.path.join(args.log_dir,
                               f"workerlog.{i}") if args.log_dir else None
            self.pod.add(Container(
                [sys.executable, "-u", args.script] + list(args.script_args),
                env, log))
        # the CONTROLLER hosts the worker barrier store for the job's life
        from ..store import MasterDaemon
        if getattr(self, "_barrier_daemon", None) is None:
            host, port = barrier_ep.rsplit(":", 1)
            self._barrier_daemon = MasterDaemon(port=int(port))

    def _watch(self) -> int:
        """PS jobs finish when all TRAINERS exit; servers are told to stop
        by worker 0 (fleet.stop_worker) or killed at teardown."""
        n_servers = getattr(self.args, "server_num", 1)
        trainers = self.pod.containers[n_servers:]
        while True:
            if all(not c.alive() for c in trainers):
                code = 0
                for c in trainers:
                    code = code or (c.exit_code or 0)
                self.pod.terminate()  # reap any server still up
                self._stop_barrier_daemon()
                return code
            failed = self.pod.failed()
            if failed is not None and failed in trainers:
                tail = failed.tail_log()
                if tail:
                    sys.stderr.write(
                        f"[launch] failed trainer log tail:\n{tail}\n")
                self.pod.terminate()
                self._stop_barrier_daemon()
                return failed.exit_code or 1
            time.sleep(self.args.poll_interval)

    def _stop_barrier_daemon(self):
        d = getattr(self, "_barrier_daemon", None)
        if d is not None:
            d.stop()
            self._barrier_daemon = None
