"""Launch CLI package (reference: python/paddle/distributed/launch/)."""
from .__main__ import launch, parse_args  # noqa: F401
from .controllers import CollectiveController  # noqa: F401
from .job import Container, Pod  # noqa: F401
