"""Process model for the launcher: Container (one trainer process) and Pod
(this node's set of containers).

Reference: python/paddle/distributed/launch/job/{container,pod}.py — the
launcher there manages GPU trainer subprocesses; here each container is one
host-process of the SPMD program (on TPU pods: exactly one per host, owning
all local chips; in CPU tests: N emulated hosts on one machine).
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional


class Container:
    def __init__(self, entrypoint: List[str], env: Dict[str, str],
                 log_path: Optional[str] = None):
        self.entrypoint = entrypoint
        self.env = env
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self._log_f = None

    def start(self):
        full_env = dict(os.environ)
        full_env.update(self.env)
        out = None
        if self.log_path:
            os.makedirs(os.path.dirname(self.log_path) or ".", exist_ok=True)
            self._log_f = open(self.log_path, "ab")
            out = self._log_f
        self.proc = subprocess.Popen(self.entrypoint, env=full_env,
                                     stdout=out, stderr=out)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def exit_code(self):
        return None if self.proc is None else self.proc.poll()

    def terminate(self, grace: float = 10.0):
        if self.proc is None or self.proc.poll() is not None:
            self._close_log()
            return
        self.proc.send_signal(signal.SIGTERM)
        deadline = time.time() + grace
        while time.time() < deadline and self.proc.poll() is None:
            time.sleep(0.1)
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self._close_log()

    def _close_log(self):
        if self._log_f:
            self._log_f.close()
            self._log_f = None

    def tail_log(self, n: int = 20) -> str:
        if not self.log_path or not os.path.exists(self.log_path):
            return ""
        with open(self.log_path, "rb") as f:
            return b"\n".join(f.read().splitlines()[-n:]).decode(
                "utf-8", "replace")


class Pod:
    def __init__(self):
        self.containers: List[Container] = []

    def add(self, c: Container):
        self.containers.append(c)

    def start(self):
        for c in self.containers:
            c.start()

    def alive(self) -> bool:
        return any(c.alive() for c in self.containers)

    def all_alive(self) -> bool:
        return all(c.alive() for c in self.containers)

    def failed(self) -> Optional[Container]:
        for c in self.containers:
            if not c.alive() and c.exit_code not in (None, 0):
                return c
        return None

    def done(self) -> bool:
        return all(not c.alive() for c in self.containers)

    def exit_code(self) -> int:
        codes = [c.exit_code or 0 for c in self.containers]
        return max(codes) if codes else 0

    def terminate(self):
        for c in self.containers:
            c.terminate()

    def clear(self):
        self.containers = []
