"""paddle.distributed.rpc analog — worker-to-worker remote calls.

Reference (SURVEY §2.2 RPC): python/paddle/distributed/rpc/rpc.py over a C++
brpc agent (fluid/distributed/rpc/) — init_rpc/rpc_sync/rpc_async/shutdown
with WorkerInfo registry. Here the transport is a per-process socket server
(pickle payloads — same trust model as the reference, which pickles python
callables over brpc) with the TCPStore as the worker registry. On TPU pods
this drives *control-plane* coordination (PS pulls, eval fan-out); the data
plane stays on XLA collectives.
"""
from __future__ import annotations

import concurrent.futures as futures
import os
import pickle
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, List, NamedTuple, Optional

from .store import TCPStore


class WorkerInfo(NamedTuple):
    name: str
    rank: int
    ip: str
    port: int


_state: Dict[str, Any] = {"workers": {}, "server": None, "self": None,
                          "store": None, "pool": None}


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return buf


class _RpcHandler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            (n,) = struct.unpack("<Q", _recv_exact(self.request, 8))
            fn, args, kwargs = pickle.loads(_recv_exact(self.request, n))
            try:
                result = (True, fn(*args, **kwargs))
            except Exception as e:  # ship the exception back
                result = (False, e)
            payload = pickle.dumps(result, protocol=4)
            self.request.sendall(struct.pack("<Q", len(payload)) + payload)
        except ConnectionError:
            pass


def init_rpc(name: str, rank: int = None, world_size: int = None,
             master_endpoint: str = None):
    """reference: paddle.distributed.rpc.init_rpc (rpc.py). Starts this
    worker's server, registers in the store, waits for the full world."""
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:0")

    socketserver.ThreadingTCPServer.allow_reuse_address = True
    socketserver.ThreadingTCPServer.daemon_threads = True
    # honor the launch rpc controller's per-worker endpoint when set
    # (launch/controllers.py RpcController); else bind an ephemeral port —
    # either way the REGISTERED store entry is the source of truth peers use
    want = os.environ.get("PADDLE_WORKER_ENDPOINT", "")
    want_port = int(want.rsplit(":", 1)[1]) if ":" in want else 0
    try:
        server = socketserver.ThreadingTCPServer(("0.0.0.0", want_port),
                                                 _RpcHandler)
    except OSError:
        server = socketserver.ThreadingTCPServer(("0.0.0.0", 0), _RpcHandler)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()

    host, mport = master_endpoint.rsplit(":", 1)
    store = TCPStore(host if host else "127.0.0.1", int(mport),
                     is_master=(rank == 0), world_size=world_size)
    ip = "127.0.0.1" if host in ("127.0.0.1", "localhost", "") else _local_ip()
    store.set(f"rpc/worker/{rank}", f"{name}|{ip}|{port}")
    workers = {}
    for r in range(world_size):
        val = store.wait(f"rpc/worker/{r}")
        wname, wip, wport = val.split("|")
        workers[wname] = WorkerInfo(wname, r, wip, int(wport))
    _state.update(server=server, store=store, workers=workers,
                  self=workers[name] if name in workers else None,
                  pool=futures.ThreadPoolExecutor(max_workers=8))
    return workers[name]


def get_worker_info(name: str) -> WorkerInfo:
    return _state["workers"][name]


def get_all_worker_infos() -> List[WorkerInfo]:
    return sorted(_state["workers"].values(), key=lambda w: w.rank)


def get_current_worker_info() -> WorkerInfo:
    return _state["self"]


def _call(to: str, fn, args, kwargs, timeout):
    w = _state["workers"][to]
    payload = pickle.dumps((fn, args or (), kwargs or {}), protocol=4)
    with socket.create_connection((w.ip, w.port), timeout=timeout) as s:
        s.sendall(struct.pack("<Q", len(payload)) + payload)
        (n,) = struct.unpack("<Q", _recv_exact(s, 8))
        ok, result = pickle.loads(_recv_exact(s, n))
    if not ok:
        raise result
    return result


def rpc_sync(to: str, fn, args=None, kwargs=None, timeout=180):
    """reference: rpc.py rpc_sync — blocking remote call."""
    return _call(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=None, kwargs=None, timeout=180):
    """reference: rpc.py rpc_async — returns a Future (.wait() alias)."""
    fut = _state["pool"].submit(_call, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # paddle Future API compat
    return fut


def shutdown():
    """reference: rpc.py shutdown — barrier then stop serving."""
    store = _state.get("store")
    if store is not None:
        try:
            store.barrier("rpc_shutdown", len(_state["workers"]), timeout=30)
        except Exception:
            pass
    server = _state.get("server")
    if server is not None:
        server.shutdown()
        server.server_close()
    if _state.get("pool") is not None:
        _state["pool"].shutdown(wait=False)
    if store is not None:
        store.close()
    _state.update(server=None, store=None, workers={}, self=None, pool=None)


def _local_ip() -> str:
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
