"""Communication API — paddle.distributed.{all_reduce, all_gather, ...}.

Reference surface: python/paddle/distributed/communication/ (+ stream.*
variants) backed by ProcessGroup tasks (process_group.h:53-368) and the
c_* collective op set (paddle/fluid/operators/collective/, SURVEY §2.2).

TPU-native semantics (single controller, SPMD):
- **Inside traced SPMD code** (a `shard_map` region — where mesh axis names
  are live), these functions lower directly to XLA collectives
  (`lax.psum/all_gather/all_to_all/ppermute`) over the group's axis. This is
  the production path: collectives ride ICI, fused and overlapped by XLA.
- **Eagerly**, a distributed program's per-rank tensors are modeled as a
  global array whose LEADING dimension is the group size (the "stacked-rank
  view"): row r is rank r's tensor. Each collective runs the same XLA
  collective over the mesh via shard_map. This replaces the reference's
  N-process + NCCL testing model (test_dist_base.py:899) with a
  deterministic single-process equivalent.

The async `Task` handles of the reference (wait()/synchronize()) have no TPU
analog — XLA program order already sequences collectives — so sync_op
arguments are accepted and ignored; a `_FakeTask` is returned for API parity.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..core.tensor import Tensor
from . import mesh as _mesh


class ReduceOp:
    """Reference: paddle.distributed.ReduceOp (communication/reduce.py)."""
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


_REDUCERS = {
    ReduceOp.SUM: lax.psum,
    ReduceOp.MAX: lax.pmax,
    ReduceOp.MIN: lax.pmin,
}


def _reduce_traced(arr, op, axis):
    """Apply a ReduceOp over a live mesh axis (local per-shard view)."""
    if op == ReduceOp.AVG:
        return lax.pmean(arr, axis)
    if op == ReduceOp.PROD:
        # no pprod primitive (log-space is wrong for <=0): gather + prod
        return jnp.prod(lax.all_gather(arr, axis), axis=0)
    return _REDUCERS[op](arr, axis)


class Group:
    """A communicator = a named mesh axis (reference: communication/group.py
    Group over a ProcessGroup; here the axis IS the communicator)."""

    _next_id = 0

    def __init__(self, mesh: Mesh, axis: str, gid: Optional[int] = None):
        self.mesh = mesh
        self.axis = axis
        if gid is None:
            Group._next_id += 1
            gid = Group._next_id
        self.id = gid

    @property
    def nranks(self) -> int:
        return self.mesh.shape[self.axis]

    @property
    def world_size(self) -> int:
        return self.nranks

    @property
    def rank(self) -> int:
        return 0  # single controller; per-shard rank = lax.axis_index in-trace

    @property
    def name(self):
        return f"mesh_axis:{self.axis}"

    @property
    def process_group(self):
        return self

    def get_group_rank(self, rank):
        return rank

    def __repr__(self):
        return f"Group(axis={self.axis!r}, nranks={self.nranks}, id={self.id})"


def _default_group() -> Group:
    m = _mesh.get_mesh()
    if m is None:
        from .parallel import init_parallel_env
        init_parallel_env()
        m = _mesh.get_mesh()
    # default group spans the whole mesh; use a flattened view
    if len(m.axis_names) == 1:
        return Group(m, m.axis_names[0], gid=0)
    flat = Mesh(np.asarray(m.devices).reshape(-1), ("world",))
    return Group(flat, "world", gid=0)


def new_group(ranks: Optional[Sequence[int]] = None, backend=None, axis: str = None) -> Group:
    """Create a group. TPU-native: groups are mesh axes, so `axis=` selects
    one; an explicit `ranks` list builds a sub-mesh over those devices
    (reference dynamic new_group → static mesh reconfig, SURVEY §7)."""
    m = _mesh.get_mesh()
    if axis is not None:
        return Group(m, axis)
    devs = np.asarray(m.devices).reshape(-1) if m is not None else np.asarray(jax.devices())
    if ranks is not None:
        devs = devs[list(ranks)]
    sub = Mesh(devs, ("sub",))
    return Group(sub, "sub")


def get_group(gid: int = 0) -> Group:
    return _default_group()


class _FakeTask:
    def wait(self):
        return True

    def is_completed(self):
        return True

    def synchronize(self):
        pass


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _require_group(group, opname):
    if group is None or not hasattr(group, "axis"):
        raise ValueError(
            f"{opname} inside shard_map-traced code needs an explicit "
            f"group= (a Group naming the live mesh axis); the default "
            f"flattened world group is not an axis of the traced mesh.")


def _unwrap(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


def _rewrap(t, arr):
    if isinstance(t, Tensor):
        t._data = arr
        t._node = None
        return t
    return Tensor(arr)


def _stacked(fn, group: Group, *arrays, out_specs=None):
    """Run `fn` (per-rank local view) over the stacked-rank leading dim."""
    ax = group.axis
    in_specs = tuple(P(ax) for _ in arrays)
    out_specs = P(ax) if out_specs is None else out_specs
    f = shard_map(fn, mesh=group.mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    return jax.jit(f)(*arrays)


def _check_group_dim(arr, group, opname):
    if arr.shape[0] != group.nranks:
        raise ValueError(
            f"{opname}: eager stacked-rank view requires leading dim == group "
            f"size ({group.nranks}), got shape {tuple(arr.shape)}. Inside "
            f"shard_map-traced code pass the local tensor instead.")


# --------------------------------------------------------------------------
# collectives
# --------------------------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group: Optional[Group] = None,
               sync_op: bool = True):
    """Reference: paddle.distributed.all_reduce (communication/all_reduce.py)
    → c_allreduce_* ops / ProcessGroup::AllReduce."""
    arr = _unwrap(tensor)
    if _is_traced(arr):
        _require_group(group, "all_reduce")
        out = _reduce_traced(arr, op, group.axis)
        return _rewrap(tensor, out) if isinstance(tensor, Tensor) else out
    group = group or _default_group()
    _check_group_dim(arr, group, "all_reduce")
    out = _stacked(lambda x: _reduce_traced(x, op, group.axis), group, arr)
    _rewrap(tensor, out)
    return _FakeTask()


def all_gather(tensor_list: Optional[List], tensor=None, group: Optional[Group] = None,
               sync_op: bool = True):
    """Reference: communication/all_gather.py — gathers each rank's tensor.
    Eager stacked view: the rows already ARE the per-rank tensors, so the
    gathered list is the unstacked rows (after an all_gather round-trip that
    validates the collective itself)."""
    if tensor is None:  # functional style: all_gather(x) -> stacked
        tensor, tensor_list = tensor_list, None
    arr = _unwrap(tensor)
    if _is_traced(arr):
        _require_group(group, "all_gather")
        out = lax.all_gather(arr, group.axis)
        return _rewrap(tensor, out) if isinstance(tensor, Tensor) else out
    group = group or _default_group()
    _check_group_dim(arr, group, "all_gather")
    gathered = _stacked(lambda x: lax.all_gather(x[0], group.axis),
                        group, arr, out_specs=P())
    rows = [Tensor(gathered[i]) for i in range(group.nranks)]
    if tensor_list is not None:
        tensor_list.extend(rows)
        return _FakeTask()
    return Tensor(jnp.stack([r._data for r in rows]))


def broadcast(tensor, src: int = 0, group: Optional[Group] = None, sync_op=True):
    """Reference: communication/broadcast.py → c_broadcast."""
    arr = _unwrap(tensor)
    if _is_traced(arr):
        _require_group(group, "broadcast")
        g = lax.all_gather(arr, group.axis)
        return _rewrap(tensor, g[src]) if isinstance(tensor, Tensor) else g[src]
    group = group or _default_group()
    _check_group_dim(arr, group, "broadcast")
    out = _stacked(lambda x: lax.all_gather(x, group.axis, axis=0, tiled=False)[src],
                   group, arr)
    _rewrap(tensor, out)
    return _FakeTask()


def reduce(tensor, dst: int = 0, op=ReduceOp.SUM, group: Optional[Group] = None,
           sync_op=True):
    """Reference: communication/reduce.py — result lands on dst; other rows
    keep their input (matches NCCL reduce semantics of undefined-but-local
    buffers; we keep them unchanged)."""
    arr = _unwrap(tensor)
    if _is_traced(arr):
        _require_group(group, "reduce")
        red = _reduce_traced(arr, op, group.axis)
        out = jnp.where(lax.axis_index(group.axis) == dst, red, arr)
        return _rewrap(tensor, out) if isinstance(tensor, Tensor) else out
    group = group or _default_group()
    _check_group_dim(arr, group, "reduce")

    def local(x):
        red = _reduce_traced(x, op, group.axis)
        i = lax.axis_index(group.axis)
        return jnp.where(i == dst, red, x)

    out = _stacked(local, group, arr)
    _rewrap(tensor, out)
    return _FakeTask()


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM,
                   group: Optional[Group] = None, sync_op=True):
    """Reference: communication/reduce_scatter.py → c_reducescatter.
    Stacked view: rows are per-rank inputs [G, G*n]; output rows are the
    scattered reduced chunks [G, n]."""
    arr = _unwrap(_rank_input(tensor, tensor_list))
    if _is_traced(arr):
        _require_group(group, "reduce_scatter")
        out = lax.psum_scatter(arr, group.axis, tiled=True)
        return _rewrap(tensor, out) if isinstance(tensor, Tensor) else out
    group = group or _default_group()
    _check_group_dim(arr, group, "reduce_scatter")
    out = _stacked(lambda x: lax.psum_scatter(x, group.axis, scatter_dimension=1,
                                              tiled=True),
                   group, arr)
    _rewrap(tensor, out)
    return _FakeTask()


def _rank_input(tensor, tensor_list):
    if tensor_list:
        return Tensor(jnp.stack([_unwrap(t) for t in tensor_list], axis=0))
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group: Optional[Group] = None,
             sync_op=True):
    """Reference: communication/all_to_all.py → alltoall op (MoE dispatch
    global_scatter/global_gather analog). Stacked view in: [G, G, ...]
    (row r = rank r's G chunks); out row r = chunk r from every rank."""
    x = _rank_input(None, in_tensor_list) if isinstance(in_tensor_list, (list, tuple)) \
        else in_tensor_list
    arr = _unwrap(x)
    if _is_traced(arr):
        _require_group(group, "alltoall")
        out = lax.all_to_all(arr, group.axis, split_axis=0, concat_axis=0, tiled=True)
        return _rewrap(x, out) if isinstance(x, Tensor) else out
    group = group or _default_group()
    _check_group_dim(arr, group, "alltoall")
    out = _stacked(
        lambda s: lax.all_to_all(s, group.axis, split_axis=1, concat_axis=1,
                                 tiled=True),
        group, arr)
    if out_tensor_list is not None:
        for i in range(group.nranks):
            out_tensor_list.append(Tensor(out[i]))
        return _FakeTask()
    return Tensor(out)


def scatter(tensor, tensor_list=None, src: int = 0, group: Optional[Group] = None,
            sync_op=True):
    """Reference: communication/scatter.py — src's tensor is split into G
    chunks along its first dim; rank r receives chunk r. Stacked view in:
    [G, d0, ...]; out: [G, d0//G, ...] (row r = chunk r of row src)."""
    arr = _unwrap(_rank_input(tensor, tensor_list))
    if _is_traced(arr):
        raise NotImplementedError(
            "scatter inside shard_map: slice by lax.axis_index directly")
    group = group or _default_group()
    _check_group_dim(arr, group, "scatter")
    G = group.nranks
    if arr.ndim < 2 or arr.shape[1] % G != 0:
        raise ValueError(f"scatter: dim 1 of stacked view {tuple(arr.shape)} "
                         f"must be divisible by group size {G}")

    def local(x):  # x: [1, d0, ...]; gather rows, keep src's chunk i
        g = lax.all_gather(x[0], group.axis)          # [G, d0, ...]
        i = lax.axis_index(group.axis)
        chunks = g[src].reshape((G, x.shape[1] // G) + x.shape[2:])
        return lax.dynamic_index_in_dim(chunks, i, axis=0, keepdims=True)

    out = _stacked(local, group, arr)
    _rewrap(tensor, out)
    return _FakeTask()


def send(tensor, dst: int, group: Optional[Group] = None, sync_op=True,
         src_rank: Optional[int] = None):
    """P2P send. TPU-native: p2p inside traced code is ppermute; eagerly the
    single controller stages the value in a mailbox keyed (dst, src)
    (reference: send_v2/recv_v2 ops). `src_rank` tags the sender — the
    single controller has no implicit rank identity, so pass it whenever
    more than one sender targets the same dst."""
    _P2P_BUF.setdefault((dst, src_rank), []).append(_unwrap(tensor))
    return _FakeTask()


def recv(tensor, src: Optional[int] = None, group: Optional[Group] = None,
         sync_op=True, rank: Optional[int] = None):
    """Receive a staged send. `rank` = the receiving rank (dst mailbox);
    `src` matches a tagged sender. Either may be omitted only when the
    pending sends make the match unambiguous."""
    keys = [k for k, box in _P2P_BUF.items() if box
            and (rank is None or k[0] == rank)
            and (src is None or k[1] is None or k[1] == src)]
    if len(keys) != 1:
        raise RuntimeError(
            f"recv(src={src}, rank={rank}): {'no' if not keys else 'ambiguous'}"
            f" pending send (pending={sorted(_P2P_BUF)}); tag send(..., "
            f"src_rank=) and pass rank= to disambiguate")
    _rewrap(tensor, _P2P_BUF[keys[0]].pop(0))
    return _FakeTask()


_P2P_BUF: dict = {}


def stream_all_reduce(*a, **k):
    return all_reduce(*a, **k)


# in-trace helpers used by parallel layers / shard_map code ------------------

def psum(x, axis: str):
    return lax.psum(x, axis)


def pmean(x, axis: str):
    return lax.pmean(x, axis)


def ppermute(x, axis: str, perm):
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    return lax.axis_index(axis)
