"""Distributed checkpointing — sharded, async, resharding on restore.

Reference (SURVEY §5.4): hybrid-parallel checkpoints live in
incubate/distributed/utils/io/dist_save.py / dist_load.py (gather state
across mp/pp/sharding groups) and auto_parallel converter.py re-shards
saved tensors when the mesh changes on resume. TPU-native: orbax is the
storage engine — every process writes its addressable shards (no gather!),
restore takes target shardings and re-lays-out arrays (the converter's job,
done by the array layer), and async save overlaps serialization with the
next training steps (orbax AsyncCheckpointer), which the reference cannot do.
"""
from __future__ import annotations

import atexit
import os
import threading
from typing import Any, Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from . import mesh as _dmesh

try:
    import orbax.checkpoint as ocp
    _OCP_ERR = None
except Exception as e:  # pragma: no cover
    ocp = None
    _OCP_ERR = str(e)


def _unwrap_tree(state):
    return jax.tree.map(
        lambda v: v._data if isinstance(v, Tensor) else v, state,
        is_leaf=lambda v: isinstance(v, Tensor))


def _wrap_tree(state):
    return jax.tree.map(
        lambda v: Tensor(v) if isinstance(v, jax.Array) else v, state)


def _require_ocp():
    if ocp is None:
        raise RuntimeError(f"orbax unavailable: {_OCP_ERR}")


class AsyncSaveHandle:
    """Returned by save_state_dict(async_save=True); wait() blocks until the
    serialization commit completes (reference has no async path — saves
    block training; SURVEY §5.4 calls for orbax-style async)."""

    def __init__(self, ckptr):
        self._ckptr = ckptr

    def wait(self):
        self._ckptr.wait_until_finished()

    def done(self) -> bool:
        try:
            return not self._ckptr._in_progress  # best-effort
        except AttributeError:
            return True


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    async_save: bool = False):
    """Save a (possibly sharded) state_dict. Every process writes only its
    addressable shards; single-host saves whole arrays.

    reference: paddle.distributed checkpoint save / dist_save.py.
    """
    _require_ocp()
    path = os.path.abspath(path)
    tree = _unwrap_tree(state_dict)
    if async_save:
        ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        ckptr.save(path, args=ocp.args.StandardSave(tree), force=True)
        return AsyncSaveHandle(ckptr)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, tree, force=True)
    # StandardCheckpointer commits in the background (orbax >= 0.11); the
    # sync API contract is "file durable on return"
    if hasattr(ckptr, "wait_until_finished"):
        ckptr.wait_until_finished()
    return None


def load_state_dict(path: str, target_state_dict: Optional[Dict] = None,
                    mesh=None) -> Dict[str, Any]:
    """Restore a state_dict, re-sharding to target layouts.

    - target_state_dict given: leaves define dtype/shape AND sharding — a
      Tensor leaf with `.pspec` set (and `mesh` or the global mesh active)
      restores sharded; this is the converter.py re-partitioning capability
      (change mesh between save and resume).
    - no target: arrays restore with their saved layout metadata.
    """
    _require_ocp()
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if target_state_dict is None:
        out = ckptr.restore(path)
        return _wrap_tree(out)

    mesh = mesh or _dmesh.get_mesh()

    def to_target(v):
        if isinstance(v, Tensor):
            aval = v._data
            sharding = None
            if v.pspec is not None and mesh is not None:
                from jax.sharding import NamedSharding
                with _dmesh.mesh_scope(mesh):
                    spec = _dmesh.filter_spec(*v.pspec)
                sharding = NamedSharding(mesh, spec)
            return jax.ShapeDtypeStruct(tuple(aval.shape), aval.dtype,
                                        sharding=sharding)
        if isinstance(v, jax.Array):
            return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
        return v

    template = jax.tree.map(to_target, target_state_dict,
                            is_leaf=lambda v: isinstance(v, Tensor))
    out = ckptr.restore(path, template)
    return _wrap_tree(out)


# One manager per target path: CheckpointManager.save serializes against
# ITS OWN in-flight async save only, and its GC sweeps every tmp.* in the
# directory — a fresh manager per dist_save call would let call N+1's GC
# delete call N's still-being-written tmp dir.
_fallback_managers: Dict[str, Any] = {}
_fallback_lock = threading.Lock()


def _drain_fallback_managers():
    # dist_save(async_save=True) writes on a daemon thread — without
    # this, a script whose LAST action is an async dist_save exits and
    # the interpreter kills the writer mid-commit, silently losing the
    # checkpoint (the orbax branch has its own completion semantics; the
    # fallback must not be lossier than the API it emulates)
    for mgr in list(_fallback_managers.values()):
        try:
            mgr.wait()
        except BaseException:
            pass


def _fallback_manager(path: str):
    from ..resilience import CheckpointManager
    key = os.path.realpath(path)
    with _fallback_lock:
        if not _fallback_managers:
            atexit.register(_drain_fallback_managers)
        mgr = _fallback_managers.get(key)
        if mgr is None:
            mgr = _fallback_managers[key] = CheckpointManager(path)
        return mgr


def dist_save(state_dict: Dict[str, Any], path: str,
              async_save: bool = False):
    """Reference-name entry point (incubate dist_save.py): persist a
    hybrid-parallel state dict. Orbax-backed where available (sharded,
    no gather); otherwise falls through to the resilience
    CheckpointManager's atomic manifest format (single-host gather —
    small models / CPU CI), so the API works on every image. Either way
    the commit is atomic: orbax commits via its own tmp+rename protocol,
    the manager via tmp.<uuid> + COMMIT marker."""
    if ocp is not None:
        return save_state_dict(state_dict, path, async_save=async_save)
    mgr = _fallback_manager(path)
    return mgr.save(0, _unwrap_tree(state_dict), async_save=async_save)


def dist_load(path: str, target_state_dict: Optional[Dict] = None,
              mesh=None) -> Dict[str, Any]:
    """Reference-name entry point (incubate dist_load.py): restore a
    dist_save checkpoint, re-sharding to `target_state_dict` layouts
    where orbax is available; the manifest fallback restores host arrays
    (verified against per-leaf checksums) wrapped as Tensors."""
    if ocp is not None:
        return load_state_dict(path, target_state_dict, mesh=mesh)
    mgr = _fallback_manager(path)
    mgr.wait()          # settle any in-flight async dist_save first
    _, state = mgr.restore_latest()

    def to_dev(v):
        # only arrays go to device; python scalars/str round-trip as-is
        # (dist_save persisted them in the manifest — jnp.asarray would
        # crash on str and turn ints/floats into 0-d Tensors)
        return jnp.asarray(v) if isinstance(v, np.ndarray) else v

    return _wrap_tree(jax.tree.map(to_dev, state))


def save_model(model, path: str, optimizer=None, async_save: bool = False):
    """Convenience: model (+optimizer) state in one checkpoint dir."""
    state = {"model": dict(model.state_dict())}
    if optimizer is not None:
        state["optimizer"] = {k: v for k, v in optimizer.state_dict().items()
                              if isinstance(v, (Tensor, jax.Array, int, float))}
    return save_state_dict(state, path, async_save=async_save)


def load_model(model, path: str, optimizer=None, mesh=None):
    target = {"model": dict(model.state_dict())}
    if optimizer is not None:
        target["optimizer"] = {k: v for k, v in optimizer.state_dict().items()
                               if isinstance(v, (Tensor, jax.Array, int, float))}
    restored = load_state_dict(path, target, mesh=mesh)
    model.set_state_dict(restored["model"])
    if optimizer is not None and "optimizer" in restored:
        optimizer.set_state_dict(restored["optimizer"])
    return restored
