"""DGC — top-k sparsified gradient exchange (real communication compression).

Reference: the DGC operator + DGCMomentumOptimizer
(paddle/fluid/operators/dgc_op.h, distributed/fleet/meta_optimizers/
dgc_optimizer.py): each worker sends only the top-k gradient entries per
step (k = (1-sparsity)·n), keeps the rest as error feedback, and the
ring-allreduce is replaced by an allgather of (values, indices) —
compressing wire bytes by ~n/(2·k·D).

TPU-native design: the dense DP gradient all-reduce is implicit in the
pjit'd step, so compressing it means stepping OUT of auto-sharding for the
exchange: `sparse_allreduce` runs under shard_map over the dp axis — each
dp shard computes a local top-k, the (values, indices) pairs ride the ICI
via all_gather (2·k·D elements instead of n), and every shard
scatter-accumulates the union into a dense tensor. `dgc_value_and_grad`
packages the whole DGC step: per-shard grads (no implicit all-reduce) →
top-k exchange → error feedback, returning the compressed global gradient
plus the new per-shard residual, ready for any optimizer's update.

The wire math (per step, per tensor of n elements over D workers):
  dense all-reduce   ≈ 2·n       elements on the ring
  DGC allgather      ≈ 2·k·D     (values+indices), k = (1-sparsity)·n
  compression ratio  = n / (k·D) (e.g. 999x sparsity, D=8 → ~125x)
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from . import mesh as _mesh


def sparse_allreduce(x, axis: str = "dp", sparsity: float = 0.999,
                     residual=None):
    """Top-k sparsified sum over mesh `axis` with error feedback.

    x:        per-shard dense tensor, REPLICATED shape (each dp shard holds
              its own local value — e.g. a local gradient).
    residual: per-shard error-feedback carry of the same shape (or None).

    Returns (global_sum_of_topk, new_residual): the dense accumulation of
    every shard's top-k entries, and what this shard kept back. Must be
    called under shard_map manual over `axis` — `dgc_value_and_grad` does
    that for you; call this directly only inside your own shard_map.
    """
    flat = x.reshape(-1).astype(jnp.float32)
    if residual is not None:
        flat = flat + residual.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    k = max(1, int(n * (1.0 - sparsity)))
    vals, idx = lax.top_k(jnp.abs(flat), k)
    sent = flat[idx]                                  # signed top-k values
    kept = flat.at[idx].set(0.0)                      # error feedback
    # exchange: allgather the (values, indices) pairs over the dp axis —
    # the 2·k·D-element wire cost that replaces the n-element all-reduce
    all_vals = lax.all_gather(sent, axis)             # [D, k]
    all_idx = lax.all_gather(idx, axis)               # [D, k]
    dense = jnp.zeros((n,), jnp.float32)
    dense = dense.at[all_idx.reshape(-1)].add(all_vals.reshape(-1))
    return dense.reshape(x.shape).astype(x.dtype), kept.reshape(x.shape)


def dgc_value_and_grad(loss_fn, params, batch, axis: str = "dp",
                       sparsity: float = 0.999, residuals=None,
                       mesh=None):
    """(loss, compressed grads, new residuals) for a pure-DP step.

    loss_fn(params, local_batch) -> scalar loss for ONE dp shard's
    microbatch (no internal psum — the DGC exchange IS the reduction).
    params are replicated; batch leaves are sharded P(axis) on dim 0;
    residuals leaves are PER-SHARD state, stored [D, *param_shape] and
    sharded P(axis) (pass None to start at zero).

    The mean over shards is folded in (sent values are pre-divided by D),
    so the result drops into any optimizer exactly where the dense
    all-reduced gradient would.
    """
    mesh = mesh or _mesh.get_mesh()
    if mesh is None:
        raise ValueError("dgc_value_and_grad needs a mesh (argument or "
                         "distributed.set_mesh/mesh_scope)")
    D = int(mesh.shape[axis])
    if residuals is None:
        residuals = [jnp.zeros((D,) + tuple(p.shape), jnp.float32)
                     for p in params]

    flat, treedef = jax.tree.flatten((list(params), list(residuals), batch))
    key = (loss_fn, mesh, axis, round(sparsity, 12), treedef,
           tuple((tuple(a.shape), str(jnp.asarray(a).dtype)) for a in flat))
    compiled = _JIT_CACHE.get(key)
    if compiled is None:
        def per_shard(params_, residuals_, batch_):
            loss, grads = jax.value_and_grad(loss_fn)(params_, batch_)
            outs, news = [], []
            for g, r in zip(grads, residuals_):
                # r arrives as this shard's [1, *shape] slice of the
                # [D, ...] per-shard state
                s, nr = sparse_allreduce(g / D, axis, sparsity,
                                         residual=r[0])
                outs.append(s)
                news.append(nr[None])
            return lax.pmean(loss, axis), outs, news

        from jax import shard_map
        bspec = jax.tree.map(lambda _: P(axis), batch)
        rspec = [P(axis)] * len(residuals)
        compiled = jax.jit(shard_map(
            per_shard, mesh=mesh, axis_names={axis},
            in_specs=(P(), rspec, bspec),
            out_specs=(P(), [P()] * len(params), rspec),
            check_vma=False))
        _JIT_CACHE[key] = compiled
    return compiled(list(params), list(residuals), batch)


_JIT_CACHE: dict = {}
