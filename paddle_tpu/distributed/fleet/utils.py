"""fleet.utils — common fleet-side helpers (reference:
python/paddle/distributed/fleet/utils/__init__.py: recompute re-export,
fs.py HDFSClient/LocalFS for PS checkpoints)."""
from __future__ import annotations

import os
import shutil

from ..recompute import recompute, recompute_sequential  # noqa: F401


class LocalFS:
    """reference: fleet/utils/fs.py LocalFS."""

    def ls_dir(self, path):
        dirs, files = [], []
        for name in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, name)) else files).append(name)
        return dirs, files

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)


class HDFSClient:
    """reference: fleet/utils/fs.py HDFSClient — requires an hadoop
    deployment; unavailable in this environment (no egress). Instantiating
    raises with guidance rather than failing deep in a save path."""

    def __init__(self, hadoop_home=None, configs=None):
        raise RuntimeError(
            "HDFSClient needs a local hadoop installation; use LocalFS or "
            "distributed.checkpoint (orbax) for shared-filesystem saves")
