"""Elastic training — restart-and-resume supervision + node membership.

Reference: python/paddle/distributed/fleet/elastic/manager.py (SURVEY §5.3):
etcd heartbeats with TTL (~60s), node join/leave triggers rank-table rebuild
and a global restart; two levels — FAULT_TOLERANCE (fixed nproc, restart on
failure) and ELASTIC (min:max nproc, scale in/out). TPU-native: the
"cluster" is host-granular (one process per host) and the store is our
TCPStore rather than etcd; on a restart the launcher reassigns
jax.distributed process ids and the coordination service rebuilds the world
(replacing the reference's rank-table env rewrite).

Resilience rewrite (ISSUE 7): ``run_with_restarts`` is the restart
supervisor the preemption contract needs — a child that exits with
``resilience.RESUME_EXIT_CODE`` ("I checkpointed, restart me") is
restarted WITHOUT charging the crash budget; ordinary crashes restart
with exponential backoff until ``max_crash_restarts`` is spent. Paired
with ``resilience.PreemptionHandler`` (emergency checkpoint on SIGTERM)
and ``CheckpointManager.restore_latest()`` in the training script, a
preempted TPU job becomes restart → resume → continue, bit-exactly
(tests/test_resilience.py proves the full loop with injected faults).
"""
from __future__ import annotations

import enum
import logging
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Union

from ..store import TCPStore

_logger = logging.getLogger(__name__)


@dataclass
class RestartReport:
    """What the supervisor saw: every run's exit code, how many were
    checkpoint-resume restarts vs crash restarts, and the final status."""
    exit_codes: List[int] = field(default_factory=list)
    resumes: int = 0
    crashes: int = 0
    final_code: int = 0

    @property
    def runs(self) -> int:
        return len(self.exit_codes)


def run_with_restarts(target: Union[Sequence[str], Callable[[], Optional[int]]],
                      *, max_crash_restarts: int = 3,
                      max_resumes: Optional[int] = None,
                      resume_code: Optional[int] = None,
                      backoff_s: float = 1.0, max_backoff_s: float = 30.0,
                      sleep: Callable[[float], None] = time.sleep,
                      on_restart: Optional[Callable] = None,
                      timeline=None) -> RestartReport:
    """Run `target` until it finishes, restarting through preemptions.

    `target` is either an argv list (run as a subprocess — the production
    launcher mode: the script saves via PreemptionHandler and exits with
    the resume-me code) or a zero-arg callable (in-process mode — returns
    an exit code or raises resilience.Preempted/SystemExit; the mode the
    chaos tests drive).

    Exit-code policy:
      0                 done — return.
      resume_code       the child checkpointed and asked to be restarted
                        (default resilience.RESUME_EXIT_CODE): restart
                        immediately, no backoff, crash budget untouched
                        (a preemptible fleet may deliver these all day —
                        `max_resumes` only exists so tests/runaway loops
                        terminate).
      anything else     a crash: restart after exponential backoff
                        (backoff_s * 2^n capped at max_backoff_s) until
                        `max_crash_restarts` is spent, then give up and
                        return the last code.

    `on_restart(kind, attempt, code)` observes every restart decision
    ("resume" | "crash").

    `timeline`: a profiler.timeline.SpanRecorder — the supervisor sees
    the whole outage (child exit → next start, backoff included), so it
    records each gap as an EXPLICIT `restart_downtime` span. The goodput
    stitcher prefers these over gap-derived downtime, so supervisor-
    recorded and derived downtime never double count."""
    if resume_code is None:
        from ...resilience import RESUME_EXIT_CODE
        resume_code = RESUME_EXIT_CODE
    report = RestartReport()
    crash_budget = max_crash_restarts
    while True:
        code = _run_once(target)
        t_exit = timeline.now() if timeline is not None else None
        report.exit_codes.append(code)
        if code == 0:
            report.final_code = 0
            return report
        if code == resume_code:
            report.resumes += 1
            if max_resumes is not None and report.resumes > max_resumes:
                report.final_code = code
                return report
            if on_restart is not None:
                on_restart("resume", report.resumes, code)
            if timeline is not None:
                timeline.record("restart_downtime", t_exit, timeline.now(),
                                kind="resume", code=code)
            continue
        report.crashes += 1
        if crash_budget <= 0:
            report.final_code = code
            return report
        crash_budget -= 1
        delay = min(backoff_s * (2.0 ** (report.crashes - 1)), max_backoff_s)
        if on_restart is not None:
            on_restart("crash", report.crashes, code)
        sleep(delay)
        if timeline is not None:
            timeline.record("restart_downtime", t_exit, timeline.now(),
                            kind="crash", code=code)


def _run_once(target) -> int:
    if callable(target):
        try:
            code = target()
        except SystemExit as e:   # incl. resilience.Preempted
            code = e.code if isinstance(e.code, int) else \
                (0 if e.code is None else 1)
        except Exception:
            # the supervisor charges its crash budget and retries — but
            # the operator debugging a crash loop needs the WHY
            _logger.exception("elastic child crashed (counted as exit 1)")
            return 1
        return int(code or 0)
    proc = subprocess.run(list(target))
    return int(proc.returncode)


class ElasticLevel:
    """reference: manager.py:41."""
    FAULT_TOLERANCE = 1
    ELASTIC = 2


class ElasticStatus(enum.Enum):
    """reference: manager.py:46."""
    COMPLETED = 0
    HOLD = 1
    RESTART = 2
    EXIT = 3
    ERROR = 4


class ElasticManager:
    """Heartbeat + membership watcher for one node.

    node key: `{job}/hb/{node_id}` = last-beat timestamp; a node is dead if
    its beat is older than `ttl`. `watch()` compares live membership to the
    membership at (re)start and returns RESTART/HOLD/COMPLETED decisions the
    launcher acts on."""

    def __init__(self, store: TCPStore, job_id: str, node_id: str,
                 np_min: int, np_max: Optional[int] = None,
                 ttl: float = 60.0, beat_interval: float = 10.0):
        self.store = store
        self.job_id = job_id
        self.node_id = node_id
        self.np_min = np_min
        self.np_max = np_max or np_min
        self.ttl = ttl
        self.beat_interval = beat_interval
        self.level = (ElasticLevel.ELASTIC if self.np_max > self.np_min
                      else ElasticLevel.FAULT_TOLERANCE)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._epoch_members: List[str] = []

    # -- heartbeats ----------------------------------------------------
    def _beat(self):
        self.store.set(f"{self.job_id}/hb/{self.node_id}", str(time.time()))

    def start(self):
        self._beat()
        self._thread = threading.Thread(target=self._beat_loop, daemon=True)
        self._thread.start()
        self._epoch_members = self.live_nodes()

    def _beat_loop(self):
        while not self._stop.wait(self.beat_interval):
            try:
                self._beat()
            except Exception:
                pass  # transient store outage; next beat retries

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        try:
            self.store.delete(f"{self.job_id}/hb/{self.node_id}")
        except Exception:
            pass

    # -- membership ----------------------------------------------------
    def live_nodes(self) -> List[str]:
        now = time.time()
        nodes = []
        for key in self.store.keys(f"{self.job_id}/hb/"):
            ts = self.store.get(key)
            if ts and now - float(ts) < self.ttl:
                nodes.append(key.rsplit("/", 1)[1])
        return sorted(nodes)

    def mark_epoch(self):
        """Record current membership as the running configuration."""
        self._epoch_members = self.live_nodes()

    def watch(self) -> ElasticStatus:
        """One membership check (reference manager.py watch loop body)."""
        live = self.live_nodes()
        n = len(live)
        if n < self.np_min:
            # below quorum: hold for rejoin, the launcher escalates to EXIT
            # after its own patience window
            return ElasticStatus.HOLD
        if live != self._epoch_members:
            if self.level == ElasticLevel.FAULT_TOLERANCE and \
                    set(self._epoch_members) <= set(live):
                # a node came back / extra joins are ignored at fixed size
                return ElasticStatus.HOLD
            return ElasticStatus.RESTART
        return ElasticStatus.COMPLETED


def rank_table(manager: ElasticManager) -> dict:
    """node_id -> rank for the current live membership (the reference writes
    this into etcd for trainers to re-read after a RESTART)."""
    return {nid: i for i, nid in enumerate(manager.live_nodes())}
