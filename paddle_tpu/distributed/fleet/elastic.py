"""Elastic training manager — node health + membership over the TCPStore.

Reference: python/paddle/distributed/fleet/elastic/manager.py (SURVEY §5.3):
etcd heartbeats with TTL (~60s), node join/leave triggers rank-table rebuild
and a global restart; two levels — FAULT_TOLERANCE (fixed nproc, restart on
failure) and ELASTIC (min:max nproc, scale in/out). TPU-native: the
"cluster" is host-granular (one process per host) and the store is our
TCPStore rather than etcd; on a restart the launcher reassigns
jax.distributed process ids and the coordination service rebuilds the world
(replacing the reference's rank-table env rewrite).
"""
from __future__ import annotations

import enum
import threading
import time
from typing import List, Optional

from ..store import TCPStore


class ElasticLevel:
    """reference: manager.py:41."""
    FAULT_TOLERANCE = 1
    ELASTIC = 2


class ElasticStatus(enum.Enum):
    """reference: manager.py:46."""
    COMPLETED = 0
    HOLD = 1
    RESTART = 2
    EXIT = 3
    ERROR = 4


class ElasticManager:
    """Heartbeat + membership watcher for one node.

    node key: `{job}/hb/{node_id}` = last-beat timestamp; a node is dead if
    its beat is older than `ttl`. `watch()` compares live membership to the
    membership at (re)start and returns RESTART/HOLD/COMPLETED decisions the
    launcher acts on."""

    def __init__(self, store: TCPStore, job_id: str, node_id: str,
                 np_min: int, np_max: Optional[int] = None,
                 ttl: float = 60.0, beat_interval: float = 10.0):
        self.store = store
        self.job_id = job_id
        self.node_id = node_id
        self.np_min = np_min
        self.np_max = np_max or np_min
        self.ttl = ttl
        self.beat_interval = beat_interval
        self.level = (ElasticLevel.ELASTIC if self.np_max > self.np_min
                      else ElasticLevel.FAULT_TOLERANCE)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._epoch_members: List[str] = []

    # -- heartbeats ----------------------------------------------------
    def _beat(self):
        self.store.set(f"{self.job_id}/hb/{self.node_id}", str(time.time()))

    def start(self):
        self._beat()
        self._thread = threading.Thread(target=self._beat_loop, daemon=True)
        self._thread.start()
        self._epoch_members = self.live_nodes()

    def _beat_loop(self):
        while not self._stop.wait(self.beat_interval):
            try:
                self._beat()
            except Exception:
                pass  # transient store outage; next beat retries

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        try:
            self.store.delete(f"{self.job_id}/hb/{self.node_id}")
        except Exception:
            pass

    # -- membership ----------------------------------------------------
    def live_nodes(self) -> List[str]:
        now = time.time()
        nodes = []
        for key in self.store.keys(f"{self.job_id}/hb/"):
            ts = self.store.get(key)
            if ts and now - float(ts) < self.ttl:
                nodes.append(key.rsplit("/", 1)[1])
        return sorted(nodes)

    def mark_epoch(self):
        """Record current membership as the running configuration."""
        self._epoch_members = self.live_nodes()

    def watch(self) -> ElasticStatus:
        """One membership check (reference manager.py watch loop body)."""
        live = self.live_nodes()
        n = len(live)
        if n < self.np_min:
            # below quorum: hold for rejoin, the launcher escalates to EXIT
            # after its own patience window
            return ElasticStatus.HOLD
        if live != self._epoch_members:
            if self.level == ElasticLevel.FAULT_TOLERANCE and \
                    set(self._epoch_members) <= set(live):
                # a node came back / extra joins are ignored at fixed size
                return ElasticStatus.HOLD
            return ElasticStatus.RESTART
        return ElasticStatus.COMPLETED


def rank_table(manager: ElasticManager) -> dict:
    """node_id -> rank for the current live membership (the reference writes
    this into etcd for trainers to re-read after a RESTART)."""
    return {nid: i for i, nid in enumerate(manager.live_nodes())}
