"""fleet — distributed training facade.

Reference: python/paddle/distributed/fleet/fleet.py — fleet.init (:169),
distributed_model (:model.py:30), distributed_optimizer (:1044), plus the
hybrid env build (:385-419). TPU-native: init builds the device mesh from
DistributedStrategy.hybrid_configs; distributed_model/optimizer mostly pass
through because parallelism is declarative (pspecs + TrainStep), not
wrapper-imposed; the wrappers that remain add the reference's semantic extras
(grad-norm clipping across groups, sharded optimizer state).
"""
from __future__ import annotations

from typing import Optional

import jax

from .strategy import DistributedStrategy
from ..topology import CommunicateTopology, HybridCommunicateGroup
from .. import mesh as _mesh
from ..parallel import init_parallel_env, get_rank, get_world_size

_fleet_state = {"initialized": False, "strategy": None, "hcg": None}


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None):
    """Reference: fleet.py:169. Builds the hybrid mesh topology."""
    strategy = strategy or DistributedStrategy()
    axes = strategy.mesh_axes()
    ndev = len(jax.devices())
    import numpy as np
    need = int(np.prod(list(axes.values()))) if axes else 1
    if not axes:
        axes = {"dp": ndev}
    elif need < ndev and ndev % need == 0:
        # remaining devices become (outer) data parallel, like fleet filling
        # dp_degree automatically (fleet.py hybrid check)
        axes = {"dp": (ndev // need) * axes.pop("dp", 1), **axes}
    mesh = _mesh.build_mesh(axes)
    topo = CommunicateTopology(
        hybrid_group_names=["data", "pipe", "sharding", "model"],
        dims=[mesh.shape.get("dp", 1), mesh.shape.get("pp", 1),
              mesh.shape.get("sdp", 1), mesh.shape.get("mp", 1)])
    hcg = HybridCommunicateGroup(topo, mesh)
    _fleet_state.update(initialized=True, strategy=strategy, hcg=hcg)
    init_parallel_env(mesh_axes=axes)
    return


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    return _fleet_state["hcg"]


def distributed_model(model):
    """Reference: fleet/model.py:30 — dispatch by parallel mode. TP layers
    already carry pspecs; DP/sharding are TrainStep shardings; PP wraps in
    the pipeline engine (distributed.pipeline)."""
    hcg = _fleet_state["hcg"]
    if hcg is None:
        raise RuntimeError("call fleet.init() first")
    mode = hcg.get_parallel_mode()
    if mode == "pipeline":
        from ..pipeline import PipelineParallel
        return PipelineParallel(model, hcg, _fleet_state["strategy"])
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """Reference: fleet.py:1044 → HybridParallelOptimizer
    (hybrid_parallel_optimizer.py:186). Sharded optimizer state falls out of
    TrainStep pspecs (state inherits the param's spec, further sharded over
    'sdp' by sharding.shard_optimizer); clipping stays global because grads
    are global-view arrays — the reference's cross-group norm reconstruction
    is unnecessary by construction."""
    st = strategy or _fleet_state["strategy"]
    if st is not None and st.sharding:
        from ..sharding import shard_optimizer_state
        shard_optimizer_state(optimizer, stage=int(st.sharding_configs.get("stage", 1)))
    return optimizer


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def is_first_worker():
    return get_rank() == 0


def barrier_worker():
    from ..parallel import barrier
    barrier()


from . import utils  # noqa: E402,F401
from . import elastic  # noqa: E402,F401
