"""fleet — distributed training facade.

Reference: python/paddle/distributed/fleet/fleet.py — fleet.init (:169),
distributed_model (:model.py:30), distributed_optimizer (:1044), plus the
hybrid env build (:385-419). TPU-native: init builds the device mesh from
DistributedStrategy.hybrid_configs; distributed_model/optimizer mostly pass
through because parallelism is declarative (pspecs + TrainStep), not
wrapper-imposed; the wrappers that remain add the reference's semantic extras
(grad-norm clipping across groups, sharded optimizer state).
"""
from __future__ import annotations

from typing import Optional

import jax

from .strategy import DistributedStrategy
from ..topology import CommunicateTopology, HybridCommunicateGroup
from .. import mesh as _mesh
from ..parallel import init_parallel_env, get_rank, get_world_size

_fleet_state = {"initialized": False, "strategy": None, "hcg": None}


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None):
    """Reference: fleet.py:169. Builds the hybrid mesh topology."""
    strategy = strategy or DistributedStrategy()
    axes = strategy.mesh_axes()
    ndev = len(jax.devices())
    import numpy as np
    need = int(np.prod(list(axes.values()))) if axes else 1
    if not axes:
        axes = {"dp": ndev}
    elif need < ndev and ndev % need == 0:
        # remaining devices become (outer) data parallel, like fleet filling
        # dp_degree automatically (fleet.py hybrid check)
        axes = {"dp": (ndev // need) * axes.pop("dp", 1), **axes}
    mesh = _mesh.build_mesh(axes)
    topo = CommunicateTopology(
        hybrid_group_names=["data", "pipe", "sharding", "model"],
        dims=[mesh.shape.get("dp", 1), mesh.shape.get("pp", 1),
              mesh.shape.get("sdp", 1), mesh.shape.get("mp", 1)])
    hcg = HybridCommunicateGroup(topo, mesh)
    _fleet_state.update(initialized=True, strategy=strategy, hcg=hcg)
    init_parallel_env(mesh_axes=axes)
    return


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    return _fleet_state["hcg"]


def distributed_model(model):
    """Reference: fleet/model.py:30 — dispatch by parallel mode. TP layers
    already carry pspecs; DP/sharding are TrainStep shardings; PP wraps in
    the pipeline engine (distributed.pipeline)."""
    hcg = _fleet_state["hcg"]
    if hcg is None:
        raise RuntimeError("call fleet.init() first")
    mode = hcg.get_parallel_mode()
    if mode == "pipeline":
        from ..pipeline import CompiledPipelineParallel, PipelineParallel
        if getattr(model, "supports_compiled_pp", False):
            # stacked-stage model (models/gpt_stacked.py contract): pp runs
            # as ONE compiled program (pipeline_spmd), not the eager GPipe loop
            return CompiledPipelineParallel(model, hcg, _fleet_state["strategy"])
        return PipelineParallel(model, hcg, _fleet_state["strategy"])
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """Reference: fleet.py:1044 → HybridParallelOptimizer
    (hybrid_parallel_optimizer.py:186). Sharded optimizer state falls out of
    TrainStep pspecs (state inherits the param's spec, further sharded over
    'sdp' by sharding.shard_optimizer); clipping stays global because grads
    are global-view arrays — the reference's cross-group norm reconstruction
    is unnecessary by construction."""
    st = strategy or _fleet_state["strategy"]
    if st is not None and st.sharding:
        from ..sharding import shard_optimizer_state
        shard_optimizer_state(optimizer, stage=int(st.sharding_configs.get("stage", 1)))
    return optimizer


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def is_first_worker():
    return get_rank() == 0


from . import utils  # noqa: E402,F401
from . import elastic  # noqa: E402,F401


# -------------------------------------------------------------- PS lifecycle
# (reference: fleet.py:635-679 — init_server/run_server on PSERVER
# processes, init_worker/stop_worker on trainers; roles from env like
# PaddleCloudRoleMaker.)

_ps_state = {"server": None}


def _role() -> str:
    return os.environ.get("TRAINING_ROLE", "TRAINER").upper()


def is_server() -> bool:
    return _role() == "PSERVER"


def is_worker() -> bool:
    return not is_server()


def server_num() -> int:
    eps = os.environ.get("PADDLE_PSERVER_ENDPOINTS", "")
    return len([e for e in eps.split(",") if e])


def server_endpoints():
    return [e for e in os.environ.get(
        "PADDLE_PSERVER_ENDPOINTS", "").split(",") if e]


def init_server(*args, **kwargs):
    """Bind this process's PS shard on PADDLE_PORT (reference:
    fleet.init_server loads tables; table creation here is lazy on first
    trainer touch)."""
    from .ps_runtime import PsServer
    port = int(os.environ.get("PADDLE_PORT", "0"))
    _ps_state["server"] = PsServer(port=port)
    return _ps_state["server"]


def run_server():
    """Serve until a trainer sends stop (reference: fleet.run_server —
    blocks for the life of the job)."""
    if _ps_state["server"] is None:
        init_server()
    _ps_state["server"].serve_forever()


def init_worker(scopes=None):
    """Trainer-side PS bring-up: wait for every server shard to answer
    ping (reference: fleet.init_worker barriers on server readiness)."""
    import socket as _s
    import time as _t
    deadline = _t.time() + 120
    for ep in server_endpoints():
        host, port = ep.rsplit(":", 1)
        while True:
            try:
                _s.create_connection((host, int(port)), timeout=2).close()
                break
            except OSError:
                if _t.time() > deadline:
                    raise TimeoutError(f"PS endpoint {ep} never came up")
                _t.sleep(0.2)


def stop_worker():
    """Reference: fleet.stop_worker — worker 0 also tells the servers to
    exit (the launch controller's job-teardown contract)."""
    try:
        wid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        wid = 0
    if wid == 0:
        from .ps_runtime import send_control
        for ep in server_endpoints():
            try:
                send_control(ep, "stop")
            except Exception:
                pass


_barrier_seq = {"n": 0}


def barrier_worker():
    """Barrier across trainers (reference: fleet.barrier_worker). PS jobs
    (PADDLE_TRAINERS_BARRIER_STORE set by the launch ps controller) use the
    job's store with a fresh key per call — trainers call it the same
    number of times in SPMD fashion; collective jobs use the collective
    barrier."""
    ep = os.environ.get("PADDLE_TRAINERS_BARRIER_STORE")
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if not ep:
        from ..parallel import barrier
        barrier()
        return
    if n <= 1:
        return
    from ..store import TCPStore
    host, port = ep.rsplit(":", 1)
    s = TCPStore(host, int(port), world_size=n)
    _barrier_seq["n"] += 1
    s.barrier(f"fleet_worker_barrier_{_barrier_seq['n']}", n)
    s.close()


import os  # noqa: E402  (used by the PS lifecycle helpers above)
