"""Parameter-server runtime: server process + remote table client.

Reference (SURVEY §2.2): the brpc PS — PSServer/PSClient (ps/service/
ps_client.h:64, server.h:62) with sharded MemorySparseTables, driven by
fleet's worker/server lifecycle (fleet.py:635-679 init_server/run_server/
init_worker/stop_worker) and launched by the launch CLI's ps controller.

TPU-native deployment: servers are plain CPU processes holding the host-RAM
SparseTables (distributed/ps.py); trainers talk to them over the same
pickle-frame protocol the rpc module uses. The dense model never touches
this path — it trains on-device via XLA; only the sparse embedding
pull/push rides the PS (the HeterPS split, redesigned per SURVEY §7).

Env contract (reference PaddleCloudRoleMaker):
    TRAINING_ROLE=PSERVER|TRAINER
    PADDLE_PSERVER_ENDPOINTS=h1:p1,h2:p2   PADDLE_PORT / POD_IP (server)
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM (trainer)
"""
from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import threading
from typing import Dict, List, Optional

from ..ps import SparseTable


def _send_frame(sock, obj):
    data = pickle.dumps(obj)
    sock.sendall(struct.pack("!I", len(data)) + data)


def _recv_frame(sock):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    n = struct.unpack("!I", hdr)[0]
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return pickle.loads(buf)


class PsServer:
    """One PS shard process: serves pull/push/merge_delta/save/load for any
    number of named tables (created on first touch with the client's
    config) until `stop` arrives. Per-table locks keep independent tables
    concurrent under the threading server; only creation takes the global
    lock."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        self.tables: Dict[str, SparseTable] = {}
        self._lock = threading.Lock()            # table-registry creation
        self._table_locks: Dict[str, threading.Lock] = {}
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        cmd, table, args = _recv_frame(self.request)
                    except (ConnectionError, EOFError):
                        return
                    try:
                        out = outer._dispatch(cmd, table, args)
                    except Exception as e:  # keep serving on bad requests
                        _send_frame(self.request, ("err", repr(e)))
                        continue
                    _send_frame(self.request, ("ok", out))
                    if cmd == "stop":
                        outer._srv.shutdown()
                        return

        socketserver.ThreadingTCPServer.allow_reuse_address = True
        socketserver.ThreadingTCPServer.daemon_threads = True
        self._srv = socketserver.ThreadingTCPServer((host, port), Handler)
        self.port = self._srv.server_address[1]

    def _dispatch(self, cmd, table, args):
        if cmd == "ping":
            return "pong"
        if cmd == "stop":
            return "bye"
        if cmd == "create":
            with self._lock:
                if table not in self.tables:
                    self.tables[table] = SparseTable(**args)
                    self._table_locks[table] = threading.Lock()
            return True
        t = self.tables[table]
        with self._table_locks[table]:
            if cmd == "pull":
                return t.pull(args)
            if cmd == "push":
                ids, grads = args
                t.push(ids, grads)
                return True
            if cmd == "push_pull":
                # one round-trip for the dense-PS hot path (transpiler):
                # apply the update, return the fresh rows
                ids, grads = args
                t.push(ids, grads)
                return t.pull(ids)
            if cmd == "merge_delta":
                ids, delta = args
                t.merge_delta(ids, delta)
                return True
            if cmd == "save":
                t.save(args)
                return True
            if cmd == "load":
                t.load(args)
                return True
            if cmd == "size":
                return len(t)
        raise ValueError(f"unknown command {cmd!r}")

    def serve_forever(self):
        """Block serving requests (reference: fleet.run_server)."""
        self._srv.serve_forever()

    def serve_in_thread(self):
        th = threading.Thread(target=self._srv.serve_forever, daemon=True)
        th.start()
        return th

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


class RemoteShard:
    """SparseTable duck-type over one PS endpoint (the PSClient of the
    reference, ps_client.h:64 — pull_sparse/push_sparse)."""

    def __init__(self, endpoint: str, table: str, dim: int,
                 optimizer: str = "adagrad", lr: float = 0.05,
                 init_scale: float = 0.01, seed: int = 0,
                 timeout: float = 60.0):
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._lock = threading.Lock()
        self.table = table
        self.dim = dim
        self.lr = lr
        self._call("create", dict(dim=dim, optimizer=optimizer, lr=lr,
                                  init_scale=init_scale, seed=seed))

    def _call(self, cmd, args=None):
        with self._lock:
            _send_frame(self._sock, (cmd, self.table, args))
            status, out = _recv_frame(self._sock)
        if status != "ok":
            raise RuntimeError(f"PS {cmd} failed: {out}")
        return out

    def pull(self, ids):
        return self._call("pull", ids)

    def push(self, ids, grads):
        return self._call("push", (ids, grads))

    def push_pull(self, ids, grads):
        """Apply the update and return fresh rows in ONE round-trip."""
        return self._call("push_pull", (ids, grads))

    def merge_delta(self, ids, delta):
        return self._call("merge_delta", (ids, delta))

    def save(self, path):
        return self._call("save", path)

    def load(self, path):
        return self._call("load", path)

    def __len__(self):
        return self._call("size")

    def stop_server(self):
        try:
            self._call("stop")
        except (RuntimeError, ConnectionError):
            pass

    def close(self):
        self._sock.close()


def connect_remote_tables(dim: int, table: str = "embedding",
                          endpoints: Optional[List[str]] = None,
                          optimizer: str = "adagrad", lr: float = 0.05,
                          init_scale: float = 0.01, seed: int = 0):
    """Shard clients for every server endpoint (id % n_endpoints routing —
    the same layout DistributedEmbedding uses locally)."""
    eps = endpoints or os.environ.get("PADDLE_PSERVER_ENDPOINTS", "").split(",")
    eps = [e for e in eps if e]
    if not eps:
        raise RuntimeError("no PS endpoints: set PADDLE_PSERVER_ENDPOINTS or "
                           "pass endpoints=")
    return [RemoteShard(e, table, dim, optimizer, lr,
                        init_scale=init_scale, seed=seed + i)
            for i, e in enumerate(eps)]


def send_control(endpoint: str, cmd: str, timeout: float = 10.0):
    """Fire a control command (ping/stop) without creating any table."""
    host, port = endpoint.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=timeout) as s:
        _send_frame(s, (cmd, "__ctl__", None))
        status, out = _recv_frame(s)
    if status != "ok":
        raise RuntimeError(f"PS {cmd} failed: {out}")
    return out
