"""DistributedStrategy — typed config bag.

Reference: fleet/base/distributed_strategy.py over
distributed_strategy.proto:307-373 (amp/recompute/sharding/pipeline/
tensor_parallel/hybrid_configs/...). Same property-bag-with-subconfigs shape
(SURVEY §5.6 keeps this deliberately), plain Python instead of protobuf; the
hybrid_configs degrees map 1:1 onto mesh axes. Adds sp_degree/ep_degree
(sequence/expert parallel) which the reference snapshot lacks.
"""
from __future__ import annotations

from typing import Any, Dict


class _SubConfig(dict):
    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k)

    def __setattr__(self, k, v):
        self[k] = v


_HYBRID_DEFAULTS = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                    "sharding_degree": 1, "sp_degree": 1, "ep_degree": 1}


class DistributedStrategy:
    def __init__(self):
        # feature switches (reference proto field names)
        self.amp = False
        self.amp_configs = _SubConfig(init_loss_scaling=32768.0, use_pure_bf16=False,
                                      custom_white_list=[], custom_black_list=[],
                                      use_fp16_guard=False, level="O1")
        self.recompute = False
        self.recompute_configs = _SubConfig(checkpoints=[], enable_offload=False)
        self.gradient_merge = False
        self.gradient_merge_configs = _SubConfig(k_steps=1, avg=True)
        self.sharding = False
        self.sharding_configs = _SubConfig(stage=1, degree=1, offload=False)
        self.pipeline = False
        self.pipeline_configs = _SubConfig(accumulate_steps=1, micro_batch_size=1,
                                           schedule_mode="1F1B")
        self.tensor_parallel = False
        self.tensor_parallel_configs = _SubConfig(tensor_parallel_degree=1)
        self.hybrid_configs = _SubConfig(**_HYBRID_DEFAULTS)
        self.sequence_parallel = False
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1  # accepted, meaningless on TPU (no NCCL)
        self.without_graph_optimization = False

    def __setattr__(self, k, v):
        if k == "hybrid_configs" and isinstance(v, dict) and not isinstance(v, _SubConfig):
            merged = _SubConfig(**_HYBRID_DEFAULTS)
            merged.update(v)
            v = merged
        elif k.endswith("_configs") and isinstance(v, dict) and not isinstance(v, _SubConfig):
            cur = self.__dict__.get(k)
            merged = _SubConfig(**cur) if isinstance(cur, dict) else _SubConfig()
            merged.update(v)
            v = merged
        object.__setattr__(self, k, v)

    def mesh_axes(self) -> Dict[str, int]:
        """hybrid degrees → mesh axes dict, in ICI-friendly order: mp (and
        sp) fastest-varying (see mesh.build_mesh layout note)."""
        h = self.hybrid_configs
        axes = {}
        for ax, key in (("pp", "pp_degree"), ("dp", "dp_degree"),
                        ("sdp", "sharding_degree"), ("ep", "ep_degree"),
                        ("sp", "sp_degree"), ("mp", "mp_degree")):
            d = int(h.get(key, 1))
            if d > 1:
                axes[ax] = d
        return axes

    def __repr__(self):
        on = [k for k, v in self.__dict__.items() if v is True]
        return f"DistributedStrategy(enabled={on}, hybrid={dict(self.hybrid_configs)})"
