"""HeterPS answer: device-resident mesh-sharded embedding cache with
host-RAM spill.

Reference (SURVEY §2.2, VERDICT r1 missing #1): HeterPS keeps hot embedding
rows in GPU hash tables with inter-device comms and spills the long tail to
CPU/SSD (framework/fleet/heter_ps/hashtable_kernel.cu, heter_comm_inl.h:1,
ps_gpu_wrapper.cc). TPU redesign per SURVEY §7 ("embedding sharding over
mesh + host offload"):

  * A fixed-capacity row cache LIVES ON DEVICE as a jax array, sharded
    P(axis, None) over the mesh — each device owns capacity/axis rows, the
    XLA gather/scatter ride ICI (the heter_comm analog).
  * Forward/backward never touch the host: lookup is `take` on the cached
    table; the backward applies a merged row-wise adagrad scatter update
    on device (the GPU-hashtable update kernel analog).
  * An id→slot map + LRU admission runs on host; misses pull rows (and
    their accumulator state) from the host-RAM spill tier (ps.SparseTable
    semantics) and evictions write cold rows back — the only h2d/d2h
    traffic, proportional to the MISS set, not the batch.
  * `prefetch(next_ids)` overlaps that admission with the current step
    (HeterPS's pull-ahead pipeline, ps_gpu_wrapper.cc BuildGPUTask).
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..autograd import PyLayer
from ..nn.layer import Layer
from . import mesh as _mesh


def _adagrad_rowwise(table, g2, slots, inv, grads, lr, eps=1e-6):
    """Merged row-sparse adagrad on device. `slots` [N] are unique slot ids
    padded with the sentinel row; `inv` maps each grad row to its slot's
    segment so duplicate ids merge BEFORE the accumulator update (the
    reference's gradient-merge push semantics, memory_sparse_table.cc)."""
    n = slots.shape[0]
    g = jax.ops.segment_sum(grads, inv, num_segments=n)
    g2n = g2.at[slots].add(g * g)
    denom = jnp.sqrt(jnp.take(g2n, slots, axis=0)) + eps
    tab = table.at[slots].add(-lr * g / denom)
    return tab, g2n


_adagrad_rowwise_jit = jax.jit(_adagrad_rowwise, donate_argnums=(0, 1))


class _CacheLookup(PyLayer):
    """take on the device cache; backward = on-device row-sparse update.
    (The pull/push pair of ps.DistributedEmbedding with both sides staying
    in HBM.)"""

    @staticmethod
    def forward(ctx, anchor, module, slots, uniq, inv, out_shape):
        ctx.module = module
        ctx.uniq = uniq
        ctx.inv = inv
        rows = jnp.take(module._table, slots, axis=0)
        return Tensor(rows.reshape(out_shape))

    @staticmethod
    def backward(ctx, dy):
        m = ctx.module
        g = dy._data.reshape(-1, m.dim).astype(jnp.float32)
        # _lock orders this read-modify-write of (_table, _g2) against the
        # prefetch() admission thread, which also updates both arrays —
        # without it the overlap pattern (prefetch(next); loss.backward())
        # can drop a whole batch's update or touch a donated buffer.
        with m._lock:
            m._table, m._g2 = _adagrad_rowwise_jit(
                m._table, m._g2, ctx.uniq, ctx.inv, g, jnp.float32(m.lr))
        return Tensor(jnp.zeros((), jnp.float32))


class MeshShardedEmbedding(Layer):
    """Device-cached sparse embedding over a mesh axis with host spill.

    capacity: number of device-resident rows (plus one internal sentinel).
    axis:     mesh axis the cache rows shard over (replicated if absent).
    Rows carry their adagrad accumulator with them when spilled/admitted, so
    cache evictions are exact (same trajectory as an infinite cache).
    """

    def __init__(self, dim: int, capacity: int = 1 << 16, axis: str = "mp",
                 lr: float = 0.05, init_scale: float = 0.01, seed: int = 0):
        super().__init__()
        self.dim = dim
        self.capacity = int(capacity)
        self.axis = axis
        self.lr = lr
        self._init_scale = init_scale
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()

        m = _mesh.get_mesh()
        ax = m.shape[axis] if (m is not None and axis in m.axis_names) else 1
        nrows = -(-(self.capacity + 1) // ax) * ax  # sentinel + axis padding
        tab = jnp.zeros((nrows, dim), jnp.float32)
        g2 = jnp.zeros((nrows, dim), jnp.float32)
        if ax > 1:
            sh = NamedSharding(m, P(axis, None))
            tab, g2 = jax.device_put(tab, sh), jax.device_put(g2, sh)
        self._table, self._g2 = tab, g2

        self._slot_of: "OrderedDict[int, int]" = OrderedDict()  # LRU order
        self._free = list(range(self.capacity - 1, -1, -1))
        # host spill tier: id -> (row, accumulator) (SparseTable semantics
        # with optimizer state carried along)
        self._spill: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        self._staged = None   # (key, slots, uniq, inv) from prefetch

    # -- host-side admission -------------------------------------------
    def _new_row(self):
        return self._rng.uniform(-self._init_scale, self._init_scale,
                                 self.dim).astype(np.float32)

    def _admit(self, flat_ids: np.ndarray):
        """Map ids -> device slots, inserting misses (from spill or fresh)
        and evicting LRU rows to spill when full. Returns (slots, uniq
        padded with sentinel, inv) as device arrays."""
        uniq_ids, first_idx, inv = np.unique(flat_ids, return_index=True,
                                             return_inverse=True)
        # insert misses in first-occurrence order — the same creation order
        # as SparseTable.pull, so init streams line up row for row
        missing = [k for k in uniq_ids[np.argsort(first_idx)].tolist()
                   if k not in self._slot_of]
        if len(uniq_ids) > self.capacity:
            raise ValueError(
                f"batch touches {len(uniq_ids)} unique ids > cache capacity "
                f"{self.capacity}; size the device cache to at least the "
                f"per-batch working set (HeterPS build-task contract)")
        if missing:
            need = len(missing) - len(self._free)
            if need > 0:
                self._evict(need, protect=set(uniq_ids.tolist()))
            ins_slots = np.empty(len(missing), np.int64)
            ins_rows = np.empty((len(missing), self.dim), np.float32)
            ins_g2 = np.zeros((len(missing), self.dim), np.float32)
            for i, k in enumerate(missing):
                slot = self._free.pop()
                self._slot_of[k] = slot
                ins_slots[i] = slot
                spilled = self._spill.pop(k, None)
                if spilled is not None:
                    ins_rows[i], ins_g2[i] = spilled
                else:
                    ins_rows[i] = self._new_row()
            self._table = self._table.at[jnp.asarray(ins_slots)].set(
                jnp.asarray(ins_rows))
            self._g2 = self._g2.at[jnp.asarray(ins_slots)].set(
                jnp.asarray(ins_g2))
        slots_np = np.empty(len(uniq_ids), np.int64)
        for i, k in enumerate(uniq_ids.tolist()):
            slots_np[i] = self._slot_of[k]
            self._slot_of.move_to_end(k)          # LRU touch
        # pad unique slots to the flat batch length so the backward's
        # segment_sum shape is static across steps (no recompiles)
        n = len(flat_ids)
        uniq_pad = np.full(n, self.capacity, np.int64)  # sentinel row
        uniq_pad[:len(uniq_ids)] = slots_np
        return (jnp.asarray(slots_np[inv]), jnp.asarray(uniq_pad),
                jnp.asarray(inv.astype(np.int32)))

    def _evict(self, n: int, protect=frozenset()):
        """Write the n least-recently-used rows (with accumulators) back to
        the host spill tier and free their slots; never evicts `protect`
        (the current batch's working set)."""
        victims = []
        for k in list(self._slot_of.keys()):
            if len(victims) >= n:
                break
            if k not in protect:
                victims.append(k)
        slots = np.array([self._slot_of[k] for k in victims], np.int64)
        rows = np.asarray(jnp.take(self._table, jnp.asarray(slots), axis=0))
        g2 = np.asarray(jnp.take(self._g2, jnp.asarray(slots), axis=0))
        for i, k in enumerate(victims):
            self._spill[k] = (rows[i], g2[i])
            del self._slot_of[k]
            self._free.append(int(slots[i]))

    # -- API ------------------------------------------------------------
    def prefetch(self, ids):
        """Stage admission for the NEXT forward (overlap with current
        step). Thread-safe with forward."""
        ids_np = np.asarray(ids._data if isinstance(ids, Tensor) else ids,
                            np.int64)
        def work():
            with self._lock:
                flat = ids_np.reshape(-1)
                self._staged = (ids_np.tobytes(), *self._admit(flat))
        t = threading.Thread(target=work, daemon=True)
        t.start()
        return t

    def forward(self, ids):
        ids_np = np.asarray(ids._data if isinstance(ids, Tensor) else ids,
                            np.int64)
        flat = ids_np.reshape(-1)
        with self._lock:
            st = self._staged
            if st is not None and st[0] == ids_np.tobytes():
                _, slots, uniq, inv = st
                self._staged = None
            else:
                slots, uniq, inv = self._admit(flat)
        anchor = Tensor(jnp.zeros((), jnp.float32), stop_gradient=False)
        out_shape = tuple(ids_np.shape) + (self.dim,)
        return _CacheLookup.apply(anchor, self, slots, uniq, inv, out_shape)

    # -- introspection / persistence ------------------------------------
    def state_size(self) -> int:
        return len(self._slot_of) + len(self._spill)

    def resident_rows(self) -> int:
        return len(self._slot_of)

    def rows_for(self, ids) -> np.ndarray:
        """Current row values for ids (device cache or spill) — test hook."""
        out = np.empty((len(ids), self.dim), np.float32)
        tab = np.asarray(self._table)
        for i, k in enumerate(ids):
            k = int(k)
            if k in self._slot_of:
                out[i] = tab[self._slot_of[k]]
            elif k in self._spill:
                out[i] = self._spill[k][0]
            else:
                raise KeyError(k)
        return out

    def save(self, path: str):
        """Spill everything then persist id->(row, g2) shards (the table
        Save contract, memory_sparse_table.cc Save)."""
        with self._lock:
            self._evict(len(self._slot_of))
            keys = np.fromiter(self._spill.keys(), np.int64, len(self._spill))
            rows = np.stack([self._spill[int(k)][0] for k in keys]) \
                if len(keys) else np.zeros((0, self.dim), np.float32)
            g2 = np.stack([self._spill[int(k)][1] for k in keys]) \
                if len(keys) else np.zeros((0, self.dim), np.float32)
            np.savez(path, keys=keys, rows=rows, g2=g2, dim=self.dim,
                     lr=self.lr)

    def load(self, path: str):
        data = np.load(path if path.endswith(".npz") else path + ".npz")
        with self._lock:
            self._spill = {int(k): (data["rows"][i], data["g2"][i])
                           for i, k in enumerate(data["keys"])}
            self._slot_of.clear()
            self._free = list(range(self.capacity - 1, -1, -1))
