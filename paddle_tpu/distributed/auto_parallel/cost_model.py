"""Auto-parallel cluster description, cost model, and mesh planner.

Reference (SURVEY §2.2 auto-parallel row): cluster.py (device/topology
JSON), cost/ + cost_model.py (per-op compute & comm cost), planner_v2.py /
tuner/ (search over distributed plans). TPU-native collapse: the plan space
is just the mesh factorization (dp × mp × pp × sp over N chips) plus remat
on/off — XLA handles op placement — so the planner is an analytic
enumerate-and-score over that small space:

  compute  = model FLOPs / (chips · peak · efficiency)
  TP comm  = per-layer activation collectives over the mp axis (ICI ring)
  DP comm  = grad all-reduce over dp (overlap-discounted)
  PP       = bubble factor (S-1)/(M+S-1) on top of compute
  memory   = params/moments/grads sharded per axis + activation estimate;
             plans that exceed per-chip HBM are rejected (the reference
             tuner's pruner) unless remat brings them under.

Numbers are estimates for RANKING plans, not predictions — the contract of
the reference's cost model too (cost/base_cost.py calibrated constants).
"""
from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class Cluster:
    """Device + interconnect description (reference: auto_parallel/cluster.py
    builds the same facts from a cluster JSON)."""
    num_chips: int = 8
    peak_flops: float = 197e12          # bf16 matmul peak per chip
    hbm_bytes: float = 15.75e9          # usable HBM per chip
    hbm_bw: float = 819e9               # bytes/s
    ici_bw: float = 45e9                # bytes/s per direction per link
    dcn_bw: float = 6.25e9              # bytes/s across slices
    mfu_ceiling: float = 0.75           # achievable fraction of peak

    PRESETS = {
        "v4": dict(peak_flops=275e12, hbm_bytes=32e9, hbm_bw=1200e9,
                   ici_bw=50e9),
        "v5e": dict(peak_flops=197e12, hbm_bytes=15.75e9, hbm_bw=819e9,
                    ici_bw=45e9),
        "v5p": dict(peak_flops=459e12, hbm_bytes=95e9, hbm_bw=2765e9,
                    ici_bw=100e9),
    }

    @classmethod
    def preset(cls, kind: str, num_chips: int) -> "Cluster":
        return cls(num_chips=num_chips, **cls.PRESETS[kind])

    @classmethod
    def from_json(cls, path: str) -> "Cluster":
        with open(path) as f:
            return cls(**json.load(f))

    def to_json(self, path: str):
        with open(path, "w") as f:
            json.dump(self.__dict__, f, indent=1)


# ------------------------------------------------------------- comm costs
def ring_all_reduce_time(nbytes: float, k: int, bw: float) -> float:
    """Ring allreduce moves 2(k-1)/k of the buffer per chip."""
    if k <= 1:
        return 0.0
    return 2 * (k - 1) / k * nbytes / bw


def all_gather_time(nbytes: float, k: int, bw: float) -> float:
    if k <= 1:
        return 0.0
    return (k - 1) / k * nbytes / bw


def all_to_all_time(nbytes: float, k: int, bw: float) -> float:
    if k <= 1:
        return 0.0
    return (k - 1) / k * nbytes / bw


@dataclass
class ModelDesc:
    """Transformer shape facts the cost model needs (GPT family)."""
    hidden: int
    layers: int
    heads: int
    vocab: int
    intermediate: Optional[int] = None
    param_bytes: int = 2                # bf16 params
    moment_bytes: int = 4               # 2 x bf16 moments
    grad_bytes: int = 2

    def __post_init__(self):
        if self.intermediate is None:
            self.intermediate = 4 * self.hidden

    @property
    def num_params(self) -> float:
        h, m = self.hidden, self.intermediate
        per_layer = 4 * h * h + 2 * h * m + 4 * h   # qkv+out + mlp + ln
        return self.layers * per_layer + self.vocab * h + 4 * h


@dataclass
class PlanCost:
    mesh: Dict[str, int]
    step_time: float                    # seconds (estimate, for ranking)
    compute_time: float
    comm_time: float
    bubble_frac: float
    mem_per_chip: float                 # bytes
    fits: bool
    use_recompute: bool = False

    def __repr__(self):
        shape = "x".join(f"{k}{v}" for k, v in self.mesh.items() if v > 1) \
            or "single"
        return (f"PlanCost({shape}: step={self.step_time*1e3:.1f}ms "
                f"comm={self.comm_time*1e3:.1f}ms mem={self.mem_per_chip/1e9:.1f}G"
                f"{' remat' if self.use_recompute else ''}"
                f"{'' if self.fits else ' OOM'})")


def estimate_plan(model: ModelDesc, cluster: Cluster, mesh: Dict[str, int],
                  batch: int, seq: int, micro_batches: int = 4,
                  use_recompute: bool = False) -> PlanCost:
    """Analytic step-time + memory for one mesh factorization."""
    dp = mesh.get("dp", 1)
    mp = mesh.get("mp", 1)
    pp = mesh.get("pp", 1)
    chips = dp * mp * pp
    h, L, m, V = model.hidden, model.layers, model.intermediate, model.vocab
    tokens = batch * seq

    # ---- compute: 6ND + attention term, split over all chips
    flops = 6 * model.num_params * tokens + 12 * L * h * seq * tokens
    if use_recompute:
        flops *= 4 / 3                          # extra forward in backward
    compute = flops / (chips * cluster.peak_flops * cluster.mfu_ceiling)

    # ---- TP comm: 2 allreduces of the activation per layer (attn out +
    # mlp down), fwd + bwd, batch sharded over dp, seq over nothing
    act_bytes = (batch // max(dp, 1)) * seq * h * 2   # bf16 activations
    tp_comm = 2 * 2 * L * ring_all_reduce_time(act_bytes, mp, cluster.ici_bw)

    # ---- DP comm: grad allreduce over dp, 50% overlappable with bwd
    grad_bytes = model.num_params / (mp * pp) * model.grad_bytes
    dp_comm = 0.5 * ring_all_reduce_time(grad_bytes, dp, cluster.ici_bw)

    # ---- PP: activation ring transfers + bubble
    pp_comm = 0.0
    bubble = 0.0
    if pp > 1:
        M = micro_batches
        bubble = (pp - 1) / (M + pp - 1)
        pp_comm = (M + pp - 1) * all_gather_time(
            act_bytes / max(M, 1), 2, cluster.ici_bw)

    comm = tp_comm + dp_comm + pp_comm
    step = (compute + comm) / max(1e-9, (1 - bubble))

    # ---- memory per chip
    p_shard = model.num_params / (mp * pp)
    mem = p_shard * (model.param_bytes + model.moment_bytes
                     + model.grad_bytes)
    # activation estimate: residual stream per layer (bwd live set), sharded
    # over dp; remat keeps ~1 layer + sqrt(L) checkpoints
    act_live = (batch / max(dp, 1)) * seq * h * 2
    layers_here = L / pp
    act_total = act_live * (4 * math.sqrt(layers_here) if use_recompute
                            else 4 * layers_here)
    mem += act_total
    mem += (V * h / mp) * model.param_bytes     # embedding shard + logits ws
    fits = mem <= cluster.hbm_bytes

    return PlanCost(mesh=dict(mesh), step_time=step, compute_time=compute,
                    comm_time=comm, bubble_frac=bubble, mem_per_chip=mem,
                    fits=fits, use_recompute=use_recompute)


def _factorizations(n: int) -> List[Tuple[int, int, int]]:
    out = []
    for dp in range(1, n + 1):
        if n % dp:
            continue
        rest = n // dp
        for mp in range(1, rest + 1):
            if rest % mp:
                continue
            out.append((dp, mp, rest // mp))
    return out


class Planner:
    """Enumerate-and-score mesh planner (reference: planner_v2.py + tuner/
    — searches distributed plans with a cost model and memory pruning)."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def tune(self, model: ModelDesc, batch: int, seq: int,
             micro_batches: int = 4, max_mp: Optional[int] = None,
             top_k: int = 5) -> List[PlanCost]:
        """Rank all (dp, mp, pp) factorizations of the cluster; plans that
        do not fit HBM are retried with recompute, and dropped if they
        still do not fit. Returns the top_k cheapest feasible plans."""
        n = self.cluster.num_chips
        plans = []
        for dp, mp, pp in _factorizations(n):
            if mp > (max_mp or model.heads):
                continue
            if model.layers % pp or (batch % (dp * micro_batches)
                                     if pp > 1 else batch % dp):
                continue
            mesh = {"dp": dp, "mp": mp, "pp": pp}
            plan = estimate_plan(model, self.cluster, mesh, batch, seq,
                                 micro_batches)
            if not plan.fits:
                plan = estimate_plan(model, self.cluster, mesh, batch, seq,
                                     micro_batches, use_recompute=True)
            if plan.fits:
                plans.append(plan)
        plans.sort(key=lambda p: p.step_time)
        return plans[:top_k]
