"""Auto-parallel (semi-automatic distributed training).

Reference: python/paddle/distributed/auto_parallel/ (SURVEY §2.2): `Engine`
(engine.py:58, fit:811, prepare:1272), `shard_tensor` annotations
(interface.py), `ProcessMesh` (process_mesh.h:32), Completer dist-attr
propagation (completion.py:107), Partitioner program split (partitioner.py:38)
and Resharder cross-mesh resharding (reshard.py:1007).

TPU-native collapse: the reference needs Completer+Partitioner+Resharder
because its executor runs per-rank program shards it must construct
explicitly. Under pjit, `shard_tensor` pins PartitionSpecs and **XLA's
sharding propagation IS the Completer**, SPMD partitioning IS the
Partitioner, and `jax.device_put` to a new NamedSharding IS the Resharder —
three subsystems become annotations plus one compiler pass. The Engine keeps
the reference's UX (prepare/fit/evaluate/predict over a strategy object).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor, Parameter
from .. import mesh as _dmesh

import weakref

# shard_tensor's mesh annotations. Side-table because Tensor has __slots__;
# keyed by id() (not WeakKeyDictionary: weakref key comparison would invoke
# the elementwise Tensor.__eq__), entries removed by finalizer on GC.
_MESH_OF: dict = {}


def _remember_mesh(x, pm):
    if id(x) not in _MESH_OF:
        weakref.finalize(x, _MESH_OF.pop, id(x), None)
    _MESH_OF[id(x)] = pm


class ProcessMesh:
    """reference: auto_parallel/process_mesh.py + process_mesh.h:32 — an
    n-dim array of device/process ids with named dims."""

    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 process_ids=None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.ndim = arr.ndim
        self.dim_names = list(dim_names) if dim_names else [
            f"d{i}" for i in range(arr.ndim)]
        self.process_ids = arr.reshape(-1).tolist()
        self._jax_mesh = None

    @property
    def mesh(self):
        return np.asarray(self.process_ids).reshape(self.shape)

    def get_dim_size(self, name: str) -> int:
        return self.shape[self.dim_names.index(name)]

    def jax_mesh(self) -> Mesh:
        """Materialize as a jax.sharding.Mesh over real devices (device i =
        process_ids[i] in jax.devices() order)."""
        if self._jax_mesh is None:
            devs = np.asarray(jax.devices())[np.asarray(self.process_ids)]
            self._jax_mesh = Mesh(devs.reshape(self.shape),
                                  axis_names=tuple(self.dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and self.shape == other.shape
                and self.process_ids == other.process_ids)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def shard_tensor(x, process_mesh: ProcessMesh = None, shard_spec: Sequence = None,
                 mesh=None, placements=None):
    """Annotate (and, for concrete tensors, place) a tensor's distribution.

    reference: auto_parallel/interface.py shard_tensor(x, process_mesh,
    shard_spec) — shard_spec entries are mesh dim names or None per tensor
    axis. The annotation is the whole mechanism here: pjit propagates it
    (completion.py:107's job) and XLA partitions accordingly.
    """
    pm = process_mesh or mesh
    spec_list = shard_spec if shard_spec is not None else placements
    spec = P(*[s if s else None for s in (spec_list or [])])
    x.pspec = spec
    if pm is not None and isinstance(pm, ProcessMesh) and isinstance(x, Tensor):
        _remember_mesh(x, pm)
    if pm is not None and isinstance(x, Tensor) and not isinstance(
            x._data, jax.ShapeDtypeStruct):
        jm = pm.jax_mesh() if isinstance(pm, ProcessMesh) else pm
        with _dmesh.mesh_scope(jm):
            fspec = _dmesh.filter_spec(*spec)
        x._data = jax.device_put(x._data, NamedSharding(jm, fspec))
    return x


def shard_op(fn, process_mesh: ProcessMesh = None, in_shard_specs=None,
             out_shard_specs=None):
    """reference: interface.py shard_op — constrain an op's output sharding
    (lowered to jax.lax.with_sharding_constraint)."""

    def wrapped(*args, **kwargs):
        out = fn(*args, **kwargs)
        if out_shard_specs and process_mesh is not None:
            jm = process_mesh.jax_mesh()
            specs = out_shard_specs[0] if isinstance(out, Tensor) else out_shard_specs
            if isinstance(out, Tensor):
                spec = P(*[s if s else None for s in specs])
                out._data = jax.lax.with_sharding_constraint(
                    out._data, NamedSharding(jm, spec))
        return out
    return wrapped


def reshard(x: Tensor, process_mesh: ProcessMesh, shard_spec: Sequence):
    """Move a concrete tensor to a different mesh/sharding (reference:
    Resharder, reshard.py:1007 — there a cross-rank send/recv planning pass;
    here one jax.device_put, XLA emits the collective permutation)."""
    return shard_tensor(x, process_mesh, shard_spec)


class Strategy:
    """reference: auto_parallel/strategy.py — config bag with sub-configs."""

    class _Sub:
        def __init__(self, **kw):
            self.__dict__.update(kw)
            self.enable = False

    def __init__(self):
        self.auto_mode = "semi"
        self.amp = Strategy._Sub(dtype="bfloat16", level="O1")
        self.recompute = Strategy._Sub(checkpoints=[])
        self.sharding = Strategy._Sub(stage=1, degree=1)
        self.gradient_merge = Strategy._Sub(k_steps=1, avg=True)
        self.dataset = None
        self.split_data = True
        self.seed = None


class Engine:
    """reference: auto_parallel/engine.py:58 — the high-level semi-auto
    trainer: prepare → fit/evaluate/predict with dist-annotated models."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy: Optional[Strategy] = None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics or []
        self.strategy = strategy or Strategy()
        self._mesh: Optional[Mesh] = None
        self._train_step = None
        self._prepared = False

    # -- mesh ----------------------------------------------------------
    def _ensure_mesh(self):
        if self._mesh is None:
            pm = _collect_mesh(self.model)
            self._mesh = pm.jax_mesh() if pm is not None else \
                _dmesh.build_mesh({"dp": len(jax.devices())})
            _dmesh.set_mesh(self._mesh)
        return self._mesh

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        """reference: engine.py:1272 — here: build the fused TrainStep over
        the mesh; XLA does completion/partitioning at first call."""
        mesh = self._ensure_mesh()
        if mode == "train":
            from ...jit.train_step import TrainStep
            if self.optimizer is None or self.loss is None:
                raise ValueError("train mode needs optimizer and loss")
            if getattr(self.strategy.sharding, "enable", False):
                from .. import sharding as _sh
                _sh.shard_optimizer_state(self.optimizer,
                                          stage=self.strategy.sharding.stage,
                                          axis="dp")
            data_axis = "dp" if "dp" in mesh.axis_names else mesh.axis_names[0]
            self._train_step = TrainStep(
                self.model, self.optimizer,
                lambda *batch: self.loss(self.model(*batch[:-1]), batch[-1]),
                mesh=mesh, data_axes=(data_axis,))
        self._prepared = True
        self.mode = mode

    # -- loops ---------------------------------------------------------
    def fit(self, train_data, train_sample_split=None, batch_size=1, epochs=1,
            steps_per_epoch=None, log_freq=10, verbose=0, **kw):
        """reference: engine.py:811. train_data: paddle_tpu.io.Dataset or
        DataLoader or (x, y) arrays."""
        if not self._prepared or self._train_step is None:
            self.prepare(mode="train")
        loader = _as_loader(train_data, batch_size)
        history = {"loss": []}
        for ep in range(epochs):
            for step, batch in enumerate(loader):
                if steps_per_epoch and step >= steps_per_epoch:
                    break
                loss = self._train_step(*_as_tensors(batch))
                history["loss"].append(float(loss))
        return history

    def evaluate(self, valid_data, batch_size=1, steps=None, **kw):
        self._ensure_mesh()
        loader = _as_loader(valid_data, batch_size)
        total, n = 0.0, 0
        self.model.eval()
        try:
            for step, batch in enumerate(loader):
                if steps and step >= steps:
                    break
                tensors = _as_tensors(batch)
                out = self.model(*tensors[:-1])
                total += float(self.loss(out, tensors[-1]))
                n += 1
        finally:
            self.model.train()
        return {"loss": total / max(n, 1)}

    def predict(self, test_data, batch_size=1, steps=None, **kw):
        self._ensure_mesh()
        loader = _as_loader(test_data, batch_size, with_labels=False)
        outs = []
        self.model.eval()
        try:
            for step, batch in enumerate(loader):
                if steps and step >= steps:
                    break
                tensors = _as_tensors(batch)
                outs.append(self.model(*tensors))
        finally:
            self.model.train()
        return outs

    def save(self, path, training=True):
        from ...framework.io import save as _save
        _save(self.model.state_dict(), path + ".pdparams")
        if training and self.optimizer is not None:
            _save(self.optimizer.state_dict(), path + ".pdopt")

    def load(self, path, strict=True, load_optimizer=True):
        from ...framework.io import load as _load
        self.model.set_state_dict(_load(path + ".pdparams"))
        import os
        if load_optimizer and self.optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self.optimizer.set_state_dict(_load(path + ".pdopt"))

    @property
    def main_program(self):  # API parity: programs are jaxprs here
        return None


# ---------------------------------------------------------------- helpers
def _collect_mesh(model) -> Optional[ProcessMesh]:
    """Find a ProcessMesh recorded by shard_tensor on any parameter."""
    if model is None:
        return None
    for _, p in model.named_parameters():
        pm = _MESH_OF.get(id(p))
        if pm is not None:
            return pm
    return None


def _as_loader(data, batch_size, with_labels=True):
    from ...io import DataLoader, Dataset
    if isinstance(data, DataLoader):
        return data
    if isinstance(data, Dataset):
        return DataLoader(data, batch_size=batch_size)
    if isinstance(data, (tuple, list)):
        arrays = [np.asarray(a) for a in data]
        n = len(arrays[0])

        class _ArrayLoader:  # re-iterable: fit() loops it once per epoch
            def __iter__(self):
                for i in range(0, n - batch_size + 1, batch_size):
                    yield tuple(a[i:i + batch_size] for a in arrays)

            def __len__(self):
                return max(0, n // batch_size)

        return _ArrayLoader()
    raise TypeError(f"unsupported data type {type(data)}")


def _as_tensors(batch):
    if isinstance(batch, (tuple, list)):
        return tuple(b if isinstance(b, Tensor) else Tensor(jnp.asarray(np.asarray(b)))
                     for b in batch)
    return (batch if isinstance(batch, Tensor) else Tensor(jnp.asarray(np.asarray(batch))),)

from .cost_model import (  # noqa: F401,E402
    Cluster, ModelDesc, PlanCost, Planner, estimate_plan,
    ring_all_reduce_time, all_gather_time, all_to_all_time,
)
