"""Quantized gradient all-reduce — int8 sync with per-chunk factored scales.

References: EQuARX (arxiv 2506.17615) — int8 ring all-reduce with
per-block scales cuts gradient-sync wire bytes ~4x at negligible accuracy
cost; T3 (arxiv 2401.16677) — per-layer gradient collectives issued as
backward materializes each layer's grads let the latency-hiding scheduler
overlap communication with the remaining backward compute.

TPU-native design: like DGC (`distributed/dgc.py`) the exchange steps OUT
of auto-sharding — `int8_psum` runs under shard_map manual over the dp
axis.  The overflow-free recipe:

  per chunk of `chunk` elements:
    amax   = max |x| over the chunk          (local)
    gmax   = pmax(amax, axis)                (tiny f32 all-reduce: the
                                              factored per-chunk scales
                                              must AGREE across shards)
    levels = 127 // D                        (D = axis size)
    scale  = max(gmax, eps) / levels
    codes  = clip(round(x / scale), ±levels).astype(int8)
    total  = psum(codes, axis)               (the int8 all-reduce; D codes
                                              of magnitude ≤ 127//D cannot
                                              overflow int8)
    out    = total * (scale / D)             (mean folded into the scale)

Wire math per step for n gradient elements over D shards (ring terms):
  f32 all-reduce   ≈ 2·n·4 bytes
  int8 all-reduce  ≈ 2·n·1 + 2·(n/chunk)·4 bytes   (codes + scale pmax)
i.e. ~3.9x fewer bytes at the default chunk of 256.

Stochastic rounding (optional) replaces round() with floor(q + u),
u ~ U[0,1) — unbiased quantization for long training runs.  The same key
is used on every shard (this jaxlib rejects `lax.axis_index` under
partial-manual lowering, r7): still unbiased, because each shard rounds
different values; shards stay bit-identical in the replicated outputs.

`TrainStep(grad_comm="int8")` wires this into the training step per
`_grad_groups` layer bucket (one collective per layer group, overlappable
with backward), with an f32 fallback for norm-sensitive leaves —
`default_f32_fallback` keeps 0/1-d params (layernorm scales, biases) in
f32; embeddings quantize by default (override via
`grad_comm_f32_fallback`).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

DEFAULT_CHUNK = 256
_EPS = 1e-30


def _pad_to_chunks(flat, chunk: int):
    n = flat.shape[0]
    n_chunks = -(-n // chunk)
    pad = n_chunks * chunk - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(n_chunks, chunk), n


def quantize_chunked(x, chunk: int = DEFAULT_CHUNK, levels: int = 127,
                     stochastic: bool = False, key=None):
    """Per-chunk symmetric int8 quantization of any tensor.

    Returns (codes int8 [n_chunks, chunk], scales f32 [n_chunks]) with the
    tail chunk zero-padded; `dequantize_chunked` undoes both. `levels` is
    the clip magnitude (127 for storage, 127//D for an overflow-free psum
    over D shards).
    """
    q, _ = _pad_to_chunks(x.reshape(-1).astype(jnp.float32), chunk)
    amax = jnp.max(jnp.abs(q), axis=1)
    scales = jnp.maximum(amax, _EPS) / levels
    q = q / scales[:, None]
    if stochastic:
        if key is None:
            raise ValueError("stochastic rounding needs a PRNG key")
        q = jnp.floor(q + jax.random.uniform(key, q.shape))
    else:
        q = jnp.round(q)
    codes = jnp.clip(q, -levels, levels).astype(jnp.int8)
    return codes, scales


def dequantize_chunked(codes, scales, n: int, shape=None,
                       dtype=jnp.float32):
    """Inverse of quantize_chunked: codes [n_chunks, chunk] x scales
    [n_chunks] -> the first `n` elements reshaped to `shape`."""
    out = (codes.astype(jnp.float32) * scales[:, None]).reshape(-1)[:n]
    if shape is not None:
        out = out.reshape(shape)
    return out.astype(dtype)


def int8_psum(x, axis: str, axis_size: int, chunk: int = DEFAULT_CHUNK,
              stochastic: bool = False, key=None, mean: bool = True):
    """Quantize -> int8 all-reduce -> dequantize over mesh `axis`.

    Must run under shard_map manual over `axis` (TrainStep's grad_comm
    wiring does this; call directly only inside your own shard_map).
    `axis_size` is the static mesh extent D — the clip level 127//D makes
    the code-sum overflow-free, so ONE int8 psum replaces the f32 ring.
    Returns the mean (default) or sum over shards, in x's dtype/shape.
    """
    levels = max(127 // int(axis_size), 1)
    flat = x.reshape(-1).astype(jnp.float32)
    q, n = _pad_to_chunks(flat, chunk)
    amax = jnp.max(jnp.abs(q), axis=1)
    gmax = lax.pmax(amax, axis)           # tiny f32 AR: shared scales
    scales = jnp.maximum(gmax, _EPS) / levels
    q = q / scales[:, None]
    if stochastic:
        if key is None:
            raise ValueError("stochastic rounding needs a PRNG key")
        q = jnp.floor(q + jax.random.uniform(key, q.shape))
    else:
        q = jnp.round(q)
    codes = jnp.clip(q, -levels, levels).astype(jnp.int8)
    total = lax.psum(codes, axis)         # the int8 all-reduce
    div = float(axis_size) if mean else 1.0
    out = (total.astype(jnp.float32) * (scales / div)[:, None]).reshape(-1)
    return out[:n].reshape(x.shape).astype(x.dtype)


def default_f32_fallback(name: str, shape: Sequence[int]) -> bool:
    """The default norm-sensitive-leaf rule: keep 0/1-d params (layernorm
    scales/biases, bias vectors) in f32 gradient sync; quantize every
    matrix/embedding.  Falling back embeddings too would sink the wire
    ratio below the 3.5x gate on embedding-heavy models — add them
    explicitly via `grad_comm_f32_fallback` if their grads prove
    norm-sensitive in YOUR run."""
    return len(shape) <= 1


def build_comm_groups(param_names: Sequence[str],
                      param_shapes: Sequence[Sequence[int]],
                      grad_groups: Sequence[Tuple[str, Sequence[int]]],
                      f32_fallback: Optional[Callable[[str, Sequence[int]],
                                                      bool]] = None):
    """Host-side bucketing plan for per-layer-group gradient sync.

    grad_groups is `debugging.grad_layer_groups()` output: [(layer_path,
    param_indices)] covering every param.  Returns [(path, quant_idxs,
    f32_idxs)] — per group, which leaves ride the int8 psum vs the f32
    fallback.  Static (shapes/names only), so the jitted step closes over
    it without retracing.
    """
    fb = f32_fallback or default_f32_fallback
    plan = []
    for path, idxs in grad_groups:
        q_idxs = [i for i in idxs
                  if not fb(param_names[i], tuple(param_shapes[i]))]
        f_idxs = [i for i in idxs if i not in set(q_idxs)]
        plan.append((path, tuple(q_idxs), tuple(f_idxs)))
    return plan


def comm_group_stats(plan, param_shapes) -> dict:
    """Static wire accounting for a build_comm_groups plan: element counts
    per lane, and the expected f32-twin vs int8 all-reduce byte ratio
    (ring terms; scale pmax traffic included)."""
    n_q = sum(int(np.prod(param_shapes[i]) or 1)
              for _, qs, _ in plan for i in qs)
    n_f = sum(int(np.prod(param_shapes[i]) or 1)
              for _, _, fs in plan for i in fs)
    total = n_q + n_f
    f32_bytes = 2 * 4 * total
    int8_bytes = (2 * 1 * n_q + 2 * 4 * -(-n_q // DEFAULT_CHUNK)
                  + 2 * 4 * n_f)
    return {"groups": len(plan), "quant_elems": n_q, "f32_elems": n_f,
            "f32_twin_bytes": f32_bytes, "int8_bytes": int8_bytes,
            "ratio": f32_bytes / max(int8_bytes, 1)}


def sync_grad_groups(grads: List, plan, axis: str, axis_size: int,
                     chunk: int = DEFAULT_CHUNK, stochastic: bool = False,
                     key=None, mean: bool = True) -> List:
    """Per-layer-group gradient sync inside shard_map manual over `axis`.

    Per group: the quantizable leaves concatenate into ONE int8_psum (one
    s8 all-reduce per layer group — the per-layer collectives XLA's
    latency-hiding scheduler overlaps with backward), the fallback leaves
    into one f32 pmean/psum.  Leaves return in their original positions,
    dtypes preserved.
    """
    out = list(grads)
    for gi, (path, q_idxs, f_idxs) in enumerate(plan):
        if q_idxs:
            parts = [grads[i].reshape(-1).astype(jnp.float32)
                     for i in q_idxs]
            cat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            k = None
            if stochastic:
                if key is None:
                    raise ValueError("stochastic rounding needs a PRNG key")
                k = jax.random.fold_in(key, gi)
            synced = int8_psum(cat, axis, axis_size, chunk=chunk,
                               stochastic=stochastic, key=k, mean=mean)
            off = 0
            for i in q_idxs:
                n = int(np.prod(grads[i].shape) or 1)
                out[i] = synced[off:off + n].reshape(
                    grads[i].shape).astype(grads[i].dtype)
                off += n
        if f_idxs:
            parts = [grads[i].reshape(-1).astype(jnp.float32)
                     for i in f_idxs]
            cat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            red = lax.pmean(cat, axis) if mean else lax.psum(cat, axis)
            off = 0
            for i in f_idxs:
                n = int(np.prod(grads[i].shape) or 1)
                out[i] = red[off:off + n].reshape(
                    grads[i].shape).astype(grads[i].dtype)
                off += n
    return out
