"""paddle.distributed.spawn analog — multiprocess SPMD entry for tests/dev.

Reference: python/paddle/distributed/spawn.py:472 — forks nprocs trainer
processes with the rank env set and joins them. Here each process becomes
one jax.distributed participant (CPU backend in tests; one per host on real
pods — where `launch` is the production path and spawn is the
single-machine convenience).
"""
from __future__ import annotations

import multiprocessing as mp
import os
import socket
from typing import Optional, Sequence


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker(fn, rank, nprocs, coordinator, devices_per_proc, args):
    os.environ["PADDLE_TPU_COORDINATOR"] = coordinator
    os.environ["PADDLE_TPU_NUM_PROCESSES"] = str(nprocs)
    os.environ["PADDLE_TPU_PROCESS_ID"] = str(rank)
    os.environ["PADDLE_TPU_LOCAL_RANK"] = str(rank)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    if devices_per_proc:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count={devices_per_proc}")
    fn(*args)


def spawn(func, args: Sequence = (), nprocs: int = -1, join: bool = True,
          daemon: bool = False, devices_per_proc: int = 0, timeout: Optional[float] = 300):
    """reference: paddle.distributed.spawn(func, args, nprocs, join)."""
    if nprocs < 1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    coordinator = f"127.0.0.1:{_free_port()}"
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, rank, nprocs, coordinator,
                              devices_per_proc, tuple(args)),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if not join:
        return procs
    for p in procs:
        p.join(timeout)
    codes = [p.exitcode for p in procs]
    if any(c is None for c in codes):  # hung worker: kill and report
        for p in procs:
            if p.exitcode is None:
                p.terminate()
                p.join(5)
        raise RuntimeError(
            f"spawned processes timed out after {timeout}s (exit codes {codes})")
    if any(c != 0 for c in codes):
        raise RuntimeError(f"spawned processes failed with exit codes {codes}")
    return procs
