"""Sequence/context-parallel attention over a mesh axis.

The reference snapshot has NO sequence parallelism — sequence length is never
partitioned (SURVEY §5.7: repo-wide grep for ring_attention/sequence_parallel
is empty; its closest primitives are c_split/c_concat,
paddle/fluid/operators/collective/). This module exceeds the reference per
the north star, with two TPU-native schedules over a named mesh axis:

- **ring**: blockwise flash-style attention; K/V shards rotate around the
  `sp` axis with `lax.ppermute` (one ICI hop per step) while each device
  accumulates an online softmax over its resident Q shard. Memory is
  O(S/n) activations per device; compute overlaps the permute because XLA
  schedules the collective-permute async against the block matmul.
- **ulysses**: head-scatter `lax.all_to_all` — re-shards [B, S/n, H, D] to
  [B, S, H/n, D], runs dense (flash) attention on full sequence per head
  group, and scatters back. Cheaper at moderate S when H % n == 0.

Both run inside `jax.shard_map` under the ambient mesh and are
differentiable (JAX transposes ppermute/all_to_all; the ring step is
`jax.checkpoint`-wrapped so the backward rematerialises block logits instead
of storing the O(S^2/n) attention matrix).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import PartitionSpec as P

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _block_attend(q, kb, vb, *, scale, causal, q_off, k_off, m, l, o):
    """One online-softmax accumulation step against K/V block (kb, vb).

    q: [B, Sq, H, D]; kb/vb: [B, Sk, H, D]; m/l: [B, H, Sq]; o: [B, H, Sq, D]
    fp32 accumulators; q_off/k_off are global position offsets for causal
    masking across blocks.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, kb,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_off + jnp.arange(q.shape[1])
        kpos = k_off + jnp.arange(kb.shape[1])
        keep = qpos[:, None] >= kpos[None, :]
        logits = jnp.where(keep[None, None], logits,
                           jnp.asarray(_NEG, logits.dtype))
    m_blk = jnp.max(logits, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(logits - m_new[..., None])
    if causal:
        p = jnp.where(keep[None, None], p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(vb.dtype), vb,
                    preferred_element_type=jnp.float32)
    o_new = o * corr[..., None] + pv
    return m_new, l_new, o_new


def _ring_local(q, k, v, *, axis_name, causal, scale):
    """shard_map body: local [B, S/n, H, D] shards; rotates K/V n times."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    m0 = jnp.full((b, h, s_loc), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    @jax.checkpoint
    def step(carry, i):
        m, l, o, kb, vb = carry
        src = (idx - i) % n           # shard that originally owned kb/vb
        m, l, o = _block_attend(q, kb, vb, scale=scale, causal=causal,
                                q_off=idx * s_loc, k_off=src * s_loc,
                                m=m, l=l, o=o)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (m, l, o, kb, vb), None

    (m, l, o, _, _), _ = lax.scan(step, (m0, l0, o0, k, v), jnp.arange(n))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def _ulysses_local(q, k, v, *, axis_name, causal, scale):
    """shard_map body: all_to_all seq<->heads, dense attention, scatter back."""
    from .attention import functional_attention

    def a2a(x, split, concat):
        return lax.all_to_all(x, axis_name, split_axis=split,
                              concat_axis=concat, tiled=True)

    qf = a2a(q, 2, 1)   # [B, S, H/n, D]
    kf = a2a(k, 2, 1)
    vf = a2a(v, 2, 1)
    # functional_attention dispatches to the Pallas flash kernel when the
    # local shapes qualify on TPU; dense fp32-softmax reference elsewhere.
    out = functional_attention(qf, kf, vf, is_causal=causal, scale=scale)
    return a2a(out, 1, 2)  # back to [B, S/n, H, D]


def _sp_attention(q, k, v, *, axis: str, causal: bool, scale: Optional[float],
                  schedule: str):
    """Dispatch sequence-parallel attention under the ambient mesh.

    q/k/v are global [B, S, H, D] arrays inside a jit trace; shard_map
    partitions S over `axis` (and rides existing dp/mp shardings on B/H).
    """
    from ..distributed import mesh as _dmesh

    mesh = _dmesh.get_mesh()
    if not schedule or mesh is None or axis not in mesh.shape \
            or mesh.shape[axis] == 1:
        from .attention import attention_reference
        return attention_reference(q, k, v, is_causal=causal, scale=scale)
    if schedule not in ("ring", "ulysses"):
        raise ValueError(f"unknown sequence-parallel schedule {schedule!r}; "
                         "expected 'ring', 'ulysses', or None")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by sp={n}")
    body = _ring_local if schedule == "ring" else _ulysses_local
    # head count seen inside shard_map is already divided by any mp sharding;
    # the all_to_all needs the LOCAL head count divisible by sp.
    local_heads = q.shape[2] // mesh.shape.get("mp", 1)
    if schedule == "ulysses" and local_heads % n:
        body = _ring_local  # heads not divisible: ring always works
    dp = "dp" if "dp" in mesh.shape else None
    mp = "mp" if "mp" in mesh.shape else None
    spec = P(dp, axis, mp, None)
    fn = shard_map(
        functools.partial(body, axis_name=axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def ring_attention(q, k, v, *, axis: str = "sp", is_causal: bool = False,
                   scale: Optional[float] = None):
    """Ring (blockwise) attention with sequence sharded over mesh axis `axis`.

    Pure-array API for jitted model code. Falls back to dense attention when
    the mesh has no such axis, so the same model runs single-chip.
    """
    return _sp_attention(q, k, v, axis=axis, causal=is_causal, scale=scale,
                         schedule="ring")


def ulysses_attention(q, k, v, *, axis: str = "sp", is_causal: bool = False,
                      scale: Optional[float] = None):
    """DeepSpeed-Ulysses-style head-alltoall sequence parallelism."""
    return _sp_attention(q, k, v, axis=axis, causal=is_causal, scale=scale,
                         schedule="ulysses")


def sequence_parallel_attention(q, k, v, *, axis: str = "sp",
                                is_causal: bool = False,
                                scale: Optional[float] = None,
                                schedule: str = "ring"):
    """Generic entry: schedule in {"ring", "ulysses"}."""
    return _sp_attention(q, k, v, axis=axis, causal=is_causal, scale=scale,
                         schedule=schedule)
